"""Overload-safe multi-tenant serving (doc/resilience.md): the lane
scheduler's strict-priority + DRR contract, the watermark shed policy,
the "queue.admit" fault site, shutdown accounting for still-incoming
batches, requeue caps and deadline flushes under concurrent tenants,
the /healthz serving-state probe, the FISHNET_NO_MULTITENANT escape
hatch, and the saturation bench's validated summary."""

import asyncio
import sys
from pathlib import Path
from types import SimpleNamespace

import pytest

sys.path.insert(0, str(Path(__file__).parent))
from fake_server import FakeServer  # noqa: E402
from test_client_e2e import make_client, wait_for  # noqa: E402
from test_protocol import ANALYSIS_ACQUIRE  # noqa: E402

from fishnet_tpu.engine.mock import MockEngineFactory
from fishnet_tpu.protocol.types import AcquireResponseBody
from fishnet_tpu.resilience import accounting, faults
from fishnet_tpu.resilience.shedding import (
    ADMIT,
    LANE_LATENCY,
    LANE_THROUGHPUT,
    SHED,
    ShedPolicy,
)
from fishnet_tpu.sched import frontend as frontend_mod
from fishnet_tpu.sched import queue as queue_mod
from fishnet_tpu.sched.queue import LaneScheduler
from fishnet_tpu.telemetry import exporter as exporter_mod
from fishnet_tpu.utils.logger import Logger
from fishnet_tpu.utils.stats import StatsRecorder

pytestmark = pytest.mark.anyio


def _pos(batch_id: str, position_id: int = 0):
    """The minimal duck-typed position the scheduler touches."""
    return SimpleNamespace(
        work=SimpleNamespace(id=batch_id), position_id=position_id
    )


# ---------------------------------------------------------------------------
# LaneScheduler units
# ---------------------------------------------------------------------------


def test_lane_scheduler_strict_priority():
    sched = LaneScheduler()
    for i in range(5):
        sched.push(_pos("bulk", i), "t0", LANE_THROUGHPUT)
    sched.push(_pos("move", 0), "t1", LANE_LATENCY)
    # The latency lane drains first even though it was pushed last.
    assert sched.pop().work.id == "move"
    assert sched.pop().work.id == "bulk"
    assert sched.depth(LANE_LATENCY) == 0
    assert sched.depth(LANE_THROUGHPUT) == 4


def test_lane_scheduler_drr_alternates_by_quantum():
    sched = LaneScheduler(quantum=8)
    for i in range(20):
        sched.push(_pos("a", i), "ta", LANE_THROUGHPUT)
        sched.push(_pos("b", i), "tb", LANE_THROUGHPUT)
    order = []
    while True:
        p = sched.pop()
        if p is None:
            break
        order.append(p.work.id)
    assert len(order) == 40
    # Quantum-sized turns, alternating tenants: a x8, b x8, a x8, ...
    assert order[:8] == ["a"] * 8
    assert order[8:16] == ["b"] * 8
    assert order[16:24] == ["a"] * 8
    assert order.count("a") == order.count("b") == 20
    assert len(sched) == 0


def test_lane_scheduler_drop_batch_and_front_push():
    sched = LaneScheduler()
    for i in range(3):
        sched.push(_pos("keep", i), "t0", LANE_THROUGHPUT)
        sched.push(_pos("drop", i), "t0", LANE_THROUGHPUT)
    assert sched.drop_batch("drop") == 3
    assert len(sched) == 3
    # A requeued position goes to the FRONT of its tenant queue.
    sched.push(_pos("keep", 99), "t0", LANE_THROUGHPUT, front=True)
    assert sched.pop().position_id == 99


# ---------------------------------------------------------------------------
# ShedPolicy units
# ---------------------------------------------------------------------------


def test_shed_policy_watermark_hysteresis():
    policy = ShedPolicy(high_watermark=10)  # low defaults to 5
    assert policy.note_depth(9) is False
    assert policy.note_depth(10) is True  # crossed high: shedding
    assert policy.note_depth(6) is True  # above low: still shedding
    assert policy.note_depth(5) is False  # at low: recovered
    assert policy.admit(LANE_THROUGHPUT, 4, throughput_depth=3,
                        latency_depth=0) == ADMIT
    assert policy.admit(LANE_THROUGHPUT, 4, throughput_depth=30,
                        latency_depth=0) == SHED
    assert policy.shed_count == 1 and policy.admit_count == 1


def test_shed_policy_latency_lane_only_bounded():
    policy = ShedPolicy(high_watermark=10)  # latency_bound = 40
    # The latency lane ignores throughput saturation...
    assert policy.admit(LANE_LATENCY, 1, throughput_depth=10_000,
                        latency_depth=0) == ADMIT
    # ...and sheds only past its own hard bound.
    assert policy.admit(LANE_LATENCY, 1, throughput_depth=0,
                        latency_depth=40) == SHED
    snap = policy.snapshot()
    assert snap["latency_bound"] == 40
    assert snap["shed_count"] == 1


def test_shed_policy_capacity_scales_with_rung_and_breaker():
    breaker_open = False
    policy = ShedPolicy(
        high_watermark=100,
        rung_fn=lambda: "xla",
        breaker_open_fn=lambda: breaker_open,
    )
    assert policy.effective_high() == 50  # xla rung halves capacity
    breaker_open = True
    assert policy.effective_high() == 25  # open breaker halves it again
    assert policy.effective_low() <= policy.effective_high()
    # A degraded plane sheds at depths a healthy one would admit.
    assert policy.admit(LANE_THROUGHPUT, 1, throughput_depth=30,
                        latency_depth=0) == SHED


async def test_queue_admit_fault_site():
    assert "queue.admit" in faults.SITES
    faults.install("queue.admit:nth=1:error")
    try:
        with pytest.raises(faults.FaultInjected):
            await faults.fire_async("queue.admit")
        await faults.fire_async("queue.admit")  # nth=1 only: second passes
        assert faults.current().counts()["queue.admit"] == 2  # site visits
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# Shutdown accounting (satellite: batches still incoming at shutdown)
# ---------------------------------------------------------------------------


class FakeApi:
    """The slice of ApiStub the queue side calls."""

    def __init__(self) -> None:
        self.endpoint = "http://fake/fishnet"
        self.tenant = ""
        self.aborted = []
        self.submitted = []

    def abort(self, batch_id: str) -> None:
        self.aborted.append(batch_id)

    def submit_analysis(self, batch_id, flavor, analysis, final=True) -> None:
        self.submitted.append(batch_id)


def _queue_pair(api: FakeApi):
    logger = Logger(verbose=0)
    rx: "asyncio.Queue" = asyncio.Queue()
    interrupt = asyncio.Event()
    state = queue_mod.QueueState(
        2, StatsRecorder(2, no_stats_file=True), logger
    )
    stub = queue_mod.QueueStub(rx, interrupt, state, api)
    actor = queue_mod.QueueActor(
        rx, interrupt, state, api, queue_mod.BacklogOpt(), logger
    )
    return state, stub, actor


async def test_queue_shutdown_abandons_scheduled_batch():
    led = accounting.install()
    try:
        api = FakeApi()
        state, stub, actor = _queue_pair(api)
        body = AcquireResponseBody.from_json(ANALYSIS_ACQUIRE)
        await actor.handle_acquired(body)
        assert "work_id" in state.pending and state.incoming_len() > 0
        stub.shutdown()
        rec = led.record("work_id")
        assert rec.terminal == "abandoned" and rec.reason == "shutdown_abort"
        assert api.aborted == ["work_id"]
        # The abandoned batch's queued positions went with it.
        assert state.incoming_len() == 0 and not state.pending
        led.assert_clean()
    finally:
        accounting.clear()


async def test_acquired_during_shutdown_abandons_through_ledger():
    # An in-flight acquire resolving AFTER shutdown() must hand the
    # batch back (accounted + aborted), not drop it on the floor.
    led = accounting.install()
    try:
        api = FakeApi()
        state, stub, actor = _queue_pair(api)
        state.shutdown_soon = True
        await actor.handle_acquired(
            AcquireResponseBody.from_json(ANALYSIS_ACQUIRE)
        )
        rec = led.record("work_id")
        assert rec.terminal == "abandoned"
        assert rec.reason == "shutdown_incoming"
        assert api.aborted == ["work_id"]
        assert not state.pending and state.incoming_len() == 0
        led.assert_clean()
    finally:
        accounting.clear()


# ---------------------------------------------------------------------------
# Requeue cap + deadline flush under concurrent tenants
# ---------------------------------------------------------------------------


async def test_requeue_generation_cap_under_concurrent_tenants():
    # Same contract as the single-stream cap test in test_resilience.py,
    # but through the multi-tenant front end: the doomed batch is
    # abandoned after MAX_REQUEUE_GENERATIONS while the other tenant's
    # stream keeps flowing.
    led = accounting.install()
    async with FakeServer() as server:
        doomed = server.lichess.add_analysis_job(moves="e2e4 e7e5 g1f3")
        survivor = server.lichess.add_analysis_job(moves="d2d4")
        factory = MockEngineFactory(fail_on="#3")
        client = make_client(
            server.endpoint, cores=1, engine_factory=factory, tenants=2
        )
        await client.start()
        assert client._frontend is not None
        assert await wait_for(lambda: survivor in server.lichess.analyses)
        assert await wait_for(
            lambda: (led.record(doomed) or None) is not None
            and led.record(doomed).terminal == "abandoned"
        )
        await client.stop(abort_pending=False)
        assert doomed not in server.lichess.analyses
    rec = led.record(doomed)
    assert rec.reason == "requeue_cap"
    assert rec.requeues == queue_mod.MAX_REQUEUE_GENERATIONS
    led.assert_clean()


async def test_deadline_flush_under_concurrent_tenants():
    # Workers park in the front end's _waiting deque when the queue is
    # empty, so the acquire rounds must drive flush_expired — a hung
    # engine's batch still flushes partially within the budget.
    led = accounting.install()
    async with FakeServer() as server:
        job = server.lichess.add_analysis_job(moves="e2e4 e7e5")
        factory = MockEngineFactory(hang_on="#1")  # ply 1 hangs forever
        client = make_client(
            server.endpoint, cores=2, engine_factory=factory,
            batch_deadline=1.0, tenants=2,
        )
        await client.start()
        assert client._frontend is not None
        assert await wait_for(
            lambda: job in server.lichess.analyses, timeout=20
        )
        body = server.lichess.analyses[job]
        await client.stop(abort_pending=True)
    parts = body["analysis"]
    assert len(parts) == 3
    assert parts[1] == {"skipped": True}  # the hung ply, flushed as skipped
    assert parts[0] is not None and parts[2] is not None
    assert server.lichess.analysis_submission_counts[job] == 1
    rec = led.record(job)
    assert rec.flushed and rec.terminal == "submitted"
    led.assert_clean()


# ---------------------------------------------------------------------------
# /healthz serving state
# ---------------------------------------------------------------------------


@pytest.fixture
def clean_health():
    with exporter_mod._HEALTH_LOCK:
        saved = dict(exporter_mod._HEALTH_PROVIDERS)
        exporter_mod._HEALTH_PROVIDERS.clear()
    yield
    with exporter_mod._HEALTH_LOCK:
        exporter_mod._HEALTH_PROVIDERS.clear()
        exporter_mod._HEALTH_PROVIDERS.update(saved)


def test_healthz_provider_states(clean_health):
    assert exporter_mod.health_snapshot() == (200, None)  # bare liveness
    exporter_mod.register_health_provider("good", lambda: {"healthy": True})
    code, body = exporter_mod.health_snapshot()
    assert code == 200 and body["status"] == "ok"
    exporter_mod.register_health_provider(
        "shedder", lambda: {"healthy": False, "shedding": True}
    )
    code, body = exporter_mod.health_snapshot()
    assert code == 503 and body["status"] == "degraded"
    exporter_mod.unregister_health_provider("shedder")
    code, _ = exporter_mod.health_snapshot()
    assert code == 200
    # A provider returning None self-unregisters (collector idiom).
    exporter_mod.register_health_provider("stale", lambda: None)
    assert exporter_mod.health_snapshot()[0] == 200
    assert "stale" not in exporter_mod._HEALTH_PROVIDERS
    # A raising provider reads as unhealthy, never a 500.
    def boom():
        raise RuntimeError("probe broke")
    exporter_mod.register_health_provider("boom", boom)
    code, body = exporter_mod.health_snapshot()
    assert code == 503
    assert body["providers"]["boom"] == {
        "healthy": False, "error": "provider raised"
    }


async def test_frontend_health_flips_with_shedding(clean_health):
    fe = frontend_mod.FrontEnd(
        "http://127.0.0.1:1/fishnet", "key", Logger(verbose=0),
        cores=1, tenants=2,
    )
    code, body = exporter_mod.health_snapshot()
    assert code == 200
    serving = body["providers"]["serving"]
    assert serving["healthy"] is True and serving["shedding"] is False
    assert set(serving["tenants"]) == {"t0", "t1"}
    fe.shed_policy.note_depth(10_000)  # saturate: hysteresis flips on
    code, body = exporter_mod.health_snapshot()
    assert code == 503
    assert body["providers"]["serving"]["shedding"] is True


# ---------------------------------------------------------------------------
# Escape hatch + saturation bench smoke
# ---------------------------------------------------------------------------


async def test_no_multitenant_env_restores_single_stream(monkeypatch):
    monkeypatch.setenv(frontend_mod.NO_MULTITENANT_ENV, "1")
    async with FakeServer() as server:
        job = server.lichess.add_analysis_job(moves="e2e4")
        client = make_client(server.endpoint, tenants=4)
        await client.start()
        assert client._frontend is None  # classic single-stream wiring
        assert await wait_for(lambda: job in server.lichess.analyses)
        await client.stop()


def test_overload_bench_smoke():
    """The acceptance run, small: 4 tenants against a saturating fake
    server — analysis sheds at the watermark, best-move p99 holds, the
    queue stays bounded, and the ledger is exactly-once throughout."""
    import bench

    summary = bench.run_overload_bench(
        seconds=5.0, tenants=4, saturation=4, high_watermark=12,
        cores=2, move_p99_budget_ms=10_000.0,
    )
    bench.validate_summary(summary)
    assert summary["mode"] == "overload"
    assert summary["ledger"]["lost"] == []
    assert summary["ledger"]["duplicated"] == []
    assert summary["queue"]["bounded"] is True
    assert summary["latency"]["move_within_budget"] is True
    assert summary["shedding"]["shed_total"] >= 1
    ratio = summary["fairness"]["ratio"]
    if ratio is not None:
        assert ratio <= 2.0
