"""Wire-model tests: parse the exact JSON bodies from doc/protocol.md and
check serialization quirks the lichess server depends on."""

import pytest

from fishnet_tpu.protocol.types import (
    AcquireResponseBody,
    AnalysisPart,
    EvalFlavor,
    NodeLimit,
    ProtocolError,
    Score,
    SkillLevel,
    Variant,
    Work,
    analysis_request_body,
    move_request_body,
)

ANALYSIS_ACQUIRE = {
    "work": {
        "type": "analysis",
        "id": "work_id",
        "nodes": {"sf15": 1500000, "sf14": 2100000, "classical": 4050000},
        "timeout": 7000,
    },
    "game_id": "abcdefgh",
    "position": "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "variant": "standard",
    "moves": "e2e4 c7c5 c2c4 b8c6 g1e2 g8f6 b1c3 c6b4 g2g3 b4d3",
    "skipPositions": [1, 4, 5],
}

MOVE_ACQUIRE = {
    "work": {
        "type": "move",
        "id": "work_id",
        "level": 5,
        "clock": {"wtime": 18000, "btime": 18000, "inc": 2},
    },
    "game_id": "",
    "position": "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "variant": "standard",
    "moves": "",
}


def test_parse_analysis_acquire():
    body = AcquireResponseBody.from_json(ANALYSIS_ACQUIRE)
    assert body.work.is_analysis
    assert body.work.id == "work_id"
    assert body.work.nodes.get(EvalFlavor.NNUE) == 1500000
    assert body.work.nodes.get(EvalFlavor.HCE) == 4050000
    assert body.work.timeout_seconds() == 7.0
    assert body.work.effective_multipv() == 1
    assert not body.work.matrix_wanted
    assert body.variant is Variant.STANDARD
    assert len(body.moves) == 10
    assert body.moves[0] == "e2e4"
    assert body.skip_positions == [1, 4, 5]
    assert body.game_id == "abcdefgh"
    assert body.batch_url("https://lichess.org/fishnet") == "https://lichess.org/abcdefgh"


def test_parse_move_acquire():
    body = AcquireResponseBody.from_json(MOVE_ACQUIRE)
    assert body.work.is_move
    assert body.work.level is SkillLevel.FIVE
    assert body.work.level.movetime_ms() == 300
    assert body.work.level.skill_level() == 7
    assert body.work.level.depth() == 5
    assert body.work.clock.wtime_ms == 180000
    assert body.work.clock.inc_ms == 2000
    assert body.work.timeout_seconds() == 2.0
    assert body.game_id is None  # empty string -> absent
    assert body.moves == []


def test_multipv_and_depth_optional():
    data = dict(ANALYSIS_ACQUIRE)
    data["work"] = dict(ANALYSIS_ACQUIRE["work"], multipv=3, depth=20)
    body = AcquireResponseBody.from_json(data)
    assert body.work.effective_multipv() == 3
    assert body.work.matrix_wanted
    assert body.work.depth == 20


def test_skill_level_tables():
    assert SkillLevel.ONE.movetime_ms() == 50
    assert SkillLevel.EIGHT.movetime_ms() == 1000
    assert SkillLevel.ONE.skill_level() == -9
    assert SkillLevel.EIGHT.skill_level() == 20
    assert SkillLevel.SEVEN.depth() == 13
    assert SkillLevel.EIGHT.depth() == 22


def test_batch_id_capacity():
    data = dict(ANALYSIS_ACQUIRE)
    data["work"] = dict(ANALYSIS_ACQUIRE["work"], id="x" * 25)
    with pytest.raises(ProtocolError):
        AcquireResponseBody.from_json(data)


def test_variant_aliases():
    assert Variant.parse("chess960").is_standard
    assert Variant.parse("fromPosition").is_standard
    assert Variant.parse("threeCheck") is Variant.THREE_CHECK
    assert Variant.parse(None).is_standard
    with pytest.raises(ProtocolError):
        Variant.parse("shogi")


def test_analysis_part_best_serialization():
    part = AnalysisPart.best(
        pv=["e2e4", "e7e5"], score=Score.cp(24), depth=18, nodes=1686023,
        time_ms=1004, nps=1670251,
    )
    assert part == {
        "pv": "e2e4 e7e5",
        "score": {"cp": 24},
        "depth": 18,
        "nodes": 1686023,
        "time": 1004,
        "nps": 1670251,
    }
    # Empty pv and unknown nps are omitted (api.rs:361-369).
    part = AnalysisPart.best(pv=[], score=Score.mate(0), depth=0, nodes=0, time_ms=0)
    assert part == {"score": {"mate": 0}, "depth": 0, "nodes": 0, "time": 0}


def test_analysis_request_body_shape():
    body = analysis_request_body(
        "2.6.8", "KEY", EvalFlavor.NNUE,
        [AnalysisPart.skipped(), None, AnalysisPart.best([], Score.cp(1), 1, 2, 3)],
    )
    assert body["fishnet"] == {"version": "2.6.8", "apikey": "KEY"}
    assert body["stockfish"] == {"flavor": "nnue"}
    assert body["analysis"][0] == {"skipped": True}
    assert body["analysis"][1] is None


def test_move_request_body():
    assert move_request_body("2.6.8", None, "b7b8q") == {
        "fishnet": {"version": "2.6.8", "apikey": ""},
        "move": {"bestmove": "b7b8q"},
    }


def test_node_limit_requires_both_fields():
    with pytest.raises(ProtocolError):
        NodeLimit.from_json({"sf15": 1})
