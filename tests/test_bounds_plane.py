"""Bound-aware search plane (doc/eval-cache.md "Bounds tier",
doc/search.md): deeper-entry-wins replacement in the process
BoundsCache and the fleet tier's bounds slots, lower/upper cutoff
semantics pinned against a reference alpha-beta over transposing game
DAGs, torn-slot read-as-miss for the new tier slot kind, service-level
harvest/seed round-trips, the FISHNET_NO_BOUNDS / FISHNET_NO_SPECULATION
escape hatches, speculative pad-row evals riding AZ dispatch padding
without perturbing results, the speculation-budget control-plane rule,
and the host linger window that fuses staggered cross-process waves
into one pow2 bucket (the SPLIT_r01 3x40 -> 192-slot pathology)."""

import asyncio
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax

sys.path.insert(0, str(Path(__file__).parent))

from fishnet_tpu.cluster import position_tier
from fishnet_tpu.models.az import AzConfig, init_az_params
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.rpc import rings
from fishnet_tpu.search import eval_cache
from fishnet_tpu.search.eval_cache import (
    BOUND_EXACT,
    BOUND_LOWER,
    BOUND_NONE,
    BOUND_UPPER,
    MOVE_NONE_BITS,
    BoundsCache,
    EvalCache,
)

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
TINY = AzConfig(channels=16, blocks=2, value_hidden=16)


# -- BoundsCache units -------------------------------------------------------


def test_bounds_cache_deeper_entry_wins():
    c = BoundsCache(capacity=64)
    assert c.insert_bound(5, 100, 90, 6, BOUND_EXACT, 123, uci="e2e4")
    # A shallower record must never clobber the resident deep one.
    assert not c.insert_bound(5, -4, 0, 3, BOUND_LOWER, 7)
    assert c.probe_bound(5) == (100, 90, 6, BOUND_EXACT, 123, "e2e4")
    # Equal depth: a non-exact bound cannot displace an exact one.
    assert not c.insert_bound(5, 1, 1, 6, BOUND_UPPER, 9)
    assert c.probe_bound(5)[3] == BOUND_EXACT
    # Strictly deeper always lands.
    assert c.insert_bound(5, 7, 8, 9, BOUND_LOWER, 11, uci="d2d4")
    assert c.probe_bound(5) == (7, 8, 9, BOUND_LOWER, 11, "d2d4")
    # BOUND_NONE and out-of-range bounds are refused outright.
    assert not c.insert_bound(6, 1, 1, 1, BOUND_NONE, 0)
    assert not c.insert_bound(6, 1, 1, 1, 4, 0)
    assert c.probe_bound(6) is None


def test_bounds_cache_block_probe_layout():
    c = BoundsCache(capacity=64)
    c.insert_bound(10, -50, -40, 4, BOUND_UPPER, 0x155)
    c.insert_bound(30, 900, 800, 7, BOUND_LOWER, 0x2AA)
    vals, evs, deps, bnds, movs = c.probe_bounds_block(
        np.array([10, 20, 30], dtype=np.uint64)
    )
    assert list(bnds) == [BOUND_UPPER, BOUND_NONE, BOUND_LOWER]
    assert list(vals) == [-50, 0, 900]
    assert list(evs) == [-40, 0, 800]
    assert list(deps) == [4, 0, 7]
    assert movs[0] == 0x155 and movs[2] == 0x2AA
    assert movs[1] == MOVE_NONE_BITS  # miss rows carry the no-move sentinel


def test_contains_is_stats_neutral():
    c = EvalCache(capacity=16)
    c.insert(7, 42)
    before = c.stats()
    assert c.contains(7) and not c.contains(8)
    after = c.stats()
    assert (after["hits"], after["misses"]) == (
        before["hits"], before["misses"],
    ), "speculation admission probes must not skew hit-rate telemetry"


# -- cutoff semantics vs reference alpha-beta --------------------------------


def _make_dag(rng, levels=5, width=6, fanout=3):
    """Depth-stratified random DAG with transpositions: level-i nodes
    draw children from the SHARED level-i+1 pool, so the same position
    is reached along many paths and TT records actually fire. Node ids
    are globally unique ints; leaves carry the static values."""
    ids = [[lvl * 1000 + i for i in range(width)] for lvl in range(levels)]
    children = {}
    for lvl in range(levels - 1):
        for node in ids[lvl]:
            k = int(rng.integers(2, fanout + 1))
            children[node] = list(
                rng.choice(ids[lvl + 1], size=k, replace=False)
            )
    values = {n: int(rng.integers(-1000, 1000)) for n in ids[-1]}
    return ids[0][0], children, values


def _negamax(children, values, node, depth):
    if depth == 0 or node not in children:
        return values.get(node, 0)
    return max(
        -_negamax(children, values, ch, depth - 1)
        for ch in children[node]
    )


INF = 10**6


def _ab_tt(children, values, node, depth, alpha, beta, tt):
    """Reference alpha-beta consuming/producing BoundsCache records
    with the native TT's cutoff rules: exact returns, lower raises
    alpha, upper lowers beta, depth-gated."""
    rec = tt.probe_bound(node)
    if rec is not None and rec[2] >= depth:
        v, _, _, b, _, _ = rec
        if b == BOUND_EXACT:
            return v
        if b == BOUND_LOWER:
            alpha = max(alpha, v)
        elif b == BOUND_UPPER:
            beta = min(beta, v)
        if alpha >= beta:
            return v
    if depth == 0 or node not in children:
        return values.get(node, 0)
    a0 = alpha
    best = -INF
    for ch in children[node]:
        best = max(
            best,
            -_ab_tt(children, values, ch, depth - 1, -beta, -alpha, tt),
        )
        alpha = max(alpha, best)
        if alpha >= beta:
            break
    bound = (
        BOUND_UPPER if best <= a0
        else BOUND_LOWER if best >= beta
        else BOUND_EXACT
    )
    tt.insert_bound(node, best, 0, depth, bound, MOVE_NONE_BITS)
    return best


def test_tt_cutoffs_match_reference_alpha_beta():
    """Lower/upper cutoff correctness: an alpha-beta consuming cached
    bound records (window narrowing + cutoff) must return the same root
    value as plain full-width negamax on transposing DAGs — and the
    cache must actually get hits, or the test proves nothing."""
    for seed in range(6):
        rng = np.random.default_rng(seed)
        root, children, values = _make_dag(rng)
        want = _negamax(children, values, root, 4)
        tt = BoundsCache(capacity=4096)
        got = _ab_tt(children, values, root, 4, -INF, INF, tt)
        assert got == want, f"seed {seed}: TT search diverged"
        # A replay over the warm table must short-circuit to the same
        # value (the exact root record makes it a single probe).
        assert _ab_tt(children, values, root, 4, -INF, INF, tt) == want
        assert tt.stats()["hits"] > 0, "DAG produced no transposition hits"


# -- fleet tier bounds slots -------------------------------------------------


@pytest.fixture
def tier_env(tmp_path, monkeypatch):
    seg = tmp_path / "tier.seg"
    monkeypatch.setenv(position_tier.TIER_ENV, "1")
    monkeypatch.setenv(position_tier.TIER_PATH_ENV, str(seg))
    monkeypatch.setenv(position_tier.TIER_CAPACITY_ENV, "4096")
    monkeypatch.setenv(position_tier.TIER_AZ_CAPACITY_ENV, "32")
    # TIER_BOUNDS_CAPACITY_ENV == FISHNET_POSITION_TIER_BOUNDS_CAPACITY
    monkeypatch.setenv(position_tier.TIER_BOUNDS_CAPACITY_ENV, "1024")
    position_tier.reset_tier()
    yield seg
    position_tier.reset_tier()


def _tier_probe(tier, keys):
    n = len(keys)
    cols = (
        np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.full(n, MOVE_NONE_BITS, np.uint32),
    )
    hits = tier.probe_bounds_block(
        np.asarray(keys, np.uint64), *cols
    )
    return hits, cols


def test_tier_bounds_roundtrip_and_deeper_wins(tier_env):
    tier = position_tier.get_tier()
    assert tier is not None
    tier.insert_bound(0xABC, -77, 12, 9, BOUND_LOWER, 0x1234)
    hits, (vals, evs, deps, bnds, movs) = _tier_probe(tier, [0xABC, 0xDEF])
    assert hits == 1
    assert (vals[0], evs[0], deps[0], bnds[0], movs[0]) == (
        -77, 12, 9, BOUND_LOWER, 0x1234,
    )
    assert bnds[1] == BOUND_NONE
    # Shallower same-key insert is refused; the deep record survives.
    tier.insert_bound(0xABC, 5, 5, 3, BOUND_EXACT, 1)
    _, (vals, _, deps, bnds, _) = _tier_probe(tier, [0xABC])
    assert (vals[0], deps[0], bnds[0]) == (-77, 9, BOUND_LOWER)
    # Deeper insert replaces.
    tier.insert_bound(0xABC, 31, 30, 12, BOUND_EXACT, 0x777)
    _, (vals, _, deps, bnds, movs) = _tier_probe(tier, [0xABC])
    assert (vals[0], deps[0], bnds[0], movs[0]) == (
        31, 12, BOUND_EXACT, 0x777,
    )
    # Block insert skips miss-marked rows.
    keys = np.array([0x111, 0x222], np.uint64)
    tier.insert_bounds_block(
        keys,
        np.array([10, 20], np.int32), np.array([1, 2], np.int32),
        np.array([4, 4], np.int32),
        np.array([BOUND_NONE, BOUND_UPPER], np.int32),
        np.array([0, 0], np.uint32),
    )
    hits, (_, _, _, bnds, _) = _tier_probe(tier, [0x111, 0x222])
    assert hits == 1 and bnds[0] == BOUND_NONE and bnds[1] == BOUND_UPPER


def test_tier_bounds_torn_slot_reads_as_miss(tier_env):
    """The SIGKILLed-writer shapes: a clobbered payload (checksum
    mismatch) and a writer dead mid-write (odd seq) must both read as
    misses for the bounds slot kind — never a value."""
    tier = position_tier.get_tier()
    tier.insert_bound(0x51, 400, 350, 8, BOUND_EXACT, 0x99)
    tier.insert_bound(0x52, -60, -50, 5, BOUND_UPPER, 0x11)
    assert _tier_probe(tier, [0x51, 0x52])[0] == 2

    def slot_of(key):
        for idx in range(len(tier._bounds)):
            if int(tier._bounds[idx]["key"]) == key:
                return idx
        raise AssertionError(f"key {key:#x} not found in bounds region")

    # Payload clobbered after publish: checksum catches it.
    tier._bounds[slot_of(0x51)]["value"] ^= 0xFF
    # Writer died mid-write: odd seq means never-published.
    tier._bounds[slot_of(0x52)]["seq"] |= 1
    hits, (_, _, _, bnds, _) = _tier_probe(tier, [0x51, 0x52])
    assert hits == 0 and not bnds.any()


# -- service harvest/seed + escape hatch -------------------------------------


def _analyses(svc, nodes=220):
    svc.set_prefetch(0, adaptive=False)

    async def go():
        out = []
        for fen, moves in (
            (STARTPOS, []),
            (STARTPOS, ["e2e4", "e7e5"]),
        ):
            r = await svc.search(fen, moves, nodes=nodes)
            out.append((
                r.best_move, r.depth, r.nodes,
                tuple((l.multipv, l.depth, l.is_mate, l.value,
                       tuple(l.pv)) for l in r.lines),
            ))
        return out

    return asyncio.run(go())


def _service(weights):
    from fishnet_tpu.search.service import SearchService

    return SearchService(
        weights=weights, pool_slots=8, batch_capacity=64,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=2,
        driver_threads=1,
    )


def test_service_bounds_harvest_then_seed(monkeypatch):
    """Cold search harvests PV bound records into the BoundsCache;
    a FRESH service (empty native TT) over the warm cache seeds its
    pool TT pre-search — the respawn-survival path the bounds tier
    exists for."""
    monkeypatch.setenv("FISHNET_NO_BOUNDS", "0")
    eval_cache.reset_cache()
    weights = NnueWeights.random(seed=3)

    svc = _service(weights)
    try:
        _analyses(svc)
        c = svc.counters()
        assert c["bounds_harvested"] > 0
        assert c["bounds_seeded"] == 0  # nothing cached before the run
    finally:
        svc.close()
    bcache = eval_cache.get_bounds_cache()
    assert bcache is not None and len(bcache) > 0
    rec = next(iter(
        bcache.probe_bound(h)
        for s in bcache._stripes for h in s
    ))
    assert rec[3] in (BOUND_UPPER, BOUND_LOWER, BOUND_EXACT)

    svc2 = _service(weights)
    try:
        _analyses(svc2)
        assert svc2.counters()["bounds_seeded"] > 0
    finally:
        svc2.close()


def test_service_bounds_hatch_is_inert(monkeypatch):
    """FISHNET_NO_BOUNDS=1 (the conftest default): no bounds cache, no
    seed/harvest calls, and fresh-service runs stay deterministic —
    the byte-for-byte arm the bench parity gate compares against."""
    assert eval_cache.bounds_disabled()
    assert eval_cache.get_bounds_cache() is None
    weights = NnueWeights.random(seed=3)
    outs = []
    for _ in range(2):
        svc = _service(weights)
        try:
            outs.append(_analyses(svc, nodes=160))
            c = svc.counters()
            assert c["bounds_harvested"] == 0
            assert c["bounds_seeded"] == 0
        finally:
            svc.close()
    assert outs[0] == outs[1]


def test_service_pad_rows_counter_advances(monkeypatch):
    """fishnet_dispatch_pad_rows_total{path="service"}: ragged NNUE
    dispatches must book their pow2 padding."""
    from fishnet_tpu.search.service import _PAD_ROWS

    before = _PAD_ROWS.value(path="service")
    svc = _service(NnueWeights.random(seed=3))
    try:
        _analyses(svc, nodes=160)
    finally:
        svc.close()
    assert _PAD_ROWS.value(path="service") > before


# -- speculative pad-row evals -----------------------------------------------


@pytest.fixture(scope="module")
def az_params():
    return init_az_params(jax.random.PRNGKey(3), TINY)


def _mcts_run(params, trees=5, visits=48, evaluator=None):
    from fishnet_tpu.search.mcts import MctsConfig, MctsPool

    cfg = MctsConfig(batch_capacity=64, az=TINY)
    pool = MctsPool(params, cfg, evaluator=evaluator)
    try:
        openings = [[], ["e2e4"], ["d2d4"], ["g1f3"], ["e2e4", "c7c5"]]
        sids = [
            pool.submit(STARTPOS, list(openings[i % len(openings)]), visits)
            for i in range(trees)
        ]
        while pool.active() > 0:
            pool.step()
        out = []
        for sid in sids:
            r = pool.harvest(sid)
            out.append((r.best_move, r.visits, r.value,
                        tuple(r.root_visits), tuple(r.pv)))
        return out, pool.counters()
    finally:
        pool.close()


def test_speculation_fills_pads_without_changing_results(
    az_params, monkeypatch
):
    """Speculative pad rows ride otherwise-wasted bucket padding: the
    hatch arm and the speculation arm must agree bit-for-bit (row
    independence), while the speculation arm lands extra rows in the
    AZ eval cache."""
    hatch_out, hatch_c = _mcts_run(az_params)  # conftest pins the hatch
    assert hatch_c["spec_offered"] == 0
    assert hatch_c["dispatch"]["spec_rows"] == 0

    monkeypatch.setenv("FISHNET_NO_SPECULATION", "0")
    eval_cache.reset_cache()
    spec_out, spec_c = _mcts_run(az_params)
    assert spec_out == hatch_out, "speculation must never perturb results"
    assert spec_c["spec_offered"] > 0
    assert spec_c["dispatch"]["spec_rows"] > 0
    # Landed rows are real cache entries (future pre-wire hits).
    az = eval_cache.get_az_cache()
    assert az is not None and az.stats()["insertions"] > 0


def test_speculation_budget_zero_pins_off(az_params, monkeypatch):
    """set_speculation_budget(0) — the controller's pin — must stop
    both the offers (tree side) and the pad fill (plane side), however
    generous the bind-time FISHNET_SPECULATION_BUDGET was."""
    from fishnet_tpu.search.az_plane import AzDispatchPlane
    from fishnet_tpu.search.mcts import MctsConfig

    monkeypatch.setenv("FISHNET_NO_SPECULATION", "0")
    eval_cache.reset_cache()
    cfg = MctsConfig(batch_capacity=64, az=TINY)
    plane = AzDispatchPlane(az_params, cfg)
    plane.set_speculation_budget(0)
    try:
        _, c = _mcts_run(az_params, evaluator=plane)
        assert c["spec_offered"] == 0
        assert plane.counters()["spec_rows"] == 0
    finally:
        plane.close()


def test_speculation_controller_pin_unpin():
    """The control-plane rule: dispatch fill above SPECULATION_PIN
    pins the budget to 0; back under SPECULATION_UNPIN restores the
    bind-time default; revert_all restores it too."""
    from fishnet_tpu.control.actuators import ActuatorRegistry
    from fishnet_tpu.control.controller import (
        RuleProbePolicy,
        standard_actuators,
    )
    from fishnet_tpu.control.signals import ControlSignals

    class FakePlane:
        def __init__(self):
            self._b = 8

        def speculation_budget(self):
            return self._b

        def set_speculation_budget(self, b):
            self._b = max(0, int(b))

    plane = FakePlane()
    reg = ActuatorRegistry()
    reg.register_all(standard_actuators(az_plane=plane))
    pol = RuleProbePolicy()

    def sig(fill):
        s = ControlSignals(window=1)
        s.counters = {"eval_steps": 5.0}
        if fill is not None:
            s.counters["dispatch_fill"] = fill
        return s

    acts = pol.decide(sig(0.95), reg.snapshot())
    assert [(a.knob, a.value) for a in acts] == [("speculation_budget", 0)]
    reg.apply(acts[0].knob, acts[0].value)
    assert plane.speculation_budget() == 0
    # Mid-band and fill-absent windows hold the pin (hysteresis).
    assert pol.decide(sig(0.7), reg.snapshot()) == []
    assert pol.decide(sig(None), reg.snapshot()) == []
    acts = pol.decide(sig(0.3), reg.snapshot())
    assert [(a.knob, a.value) for a in acts] == [
        ("speculation_budget", None)
    ]
    reg.apply(acts[0].knob, acts[0].value)
    assert plane.speculation_budget() == 8
    # The escape hatch restores the bind-time default from a pin too.
    reg.apply("speculation_budget", 0)
    reg.revert_all()
    assert plane.speculation_budget() == 8


# -- host linger: cross-process pow2 fusion (SPLIT_r01) ----------------------


def test_host_linger_fuses_staggered_waves(tmp_path):
    """Three frontends' 40-row waves landing WITHIN one linger window
    (``linger_s`` here; FISHNET_HOST_LINGER_MS / --linger-ms in
    production) must dispatch as one fused 128-slot bucket (120 rows +
    8 pads), not three 64-slot buckets (192 slots) — the SPLIT_r01
    pow2 pathology."""
    from fishnet_tpu.nnue.jax_eval import params_from_weights
    from fishnet_tpu.rpc.host import EvaluatorHost

    params = params_from_weights(NnueWeights.random(seed=5))
    host = EvaluatorHost(
        nnue_params=params, rpc_dir=str(tmp_path), linger_s=0.6,
    )
    fronts = [
        rings.create_frontend_link(str(tmp_path), name=f"f{i}.ring")
        for i in range(3)
    ]
    rng = np.random.default_rng(0)

    def payload():
        feats = rng.integers(0, 1000, (40, 2, 32), dtype=np.uint16)
        buckets = rng.integers(0, 8, 40, dtype=np.int32)
        parents = np.full(40, -1, np.int32)
        material = rng.integers(-100, 100, 40, dtype=np.int32)
        return rings.pack_nnue_submit(feats, buckets, parents, material)

    before = rings.stats()
    try:
        fronts[0].push(
            rings.KIND_NNUE_SUBMIT, 1, fronts[0].frontend_epoch, 40,
            payload(),
        )

        def late_pushes():
            for delay, front in ((0.1, fronts[1]), (0.1, fronts[2])):
                time.sleep(delay)
                front.push(
                    rings.KIND_NNUE_SUBMIT, 1, front.frontend_epoch, 40,
                    payload(),
                )

        th = threading.Thread(target=late_pushes)
        th.start()
        served = host.sweep()  # first drain sees ONE wave; linger fuses
        th.join(timeout=10.0)
        assert served == 3
        after = rings.stats()

        def delta(key):
            return after.get(key, 0) - before.get(key, 0)

        assert delta("fused.rows.nnue") == 120
        assert delta("fused.slots.nnue") <= 128, (
            "staggered waves must bucket by FUSED row count"
        )
        assert delta("pad.rows") == 8
    finally:
        host.close()
        for front in fronts:
            front.close()
