"""NNUE data pipeline: playouts -> teacher labeling -> trainer step."""

import numpy as np
import pytest

import jax.numpy as jnp

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import SearchService
from fishnet_tpu.train import NetConfig, Trainer
from fishnet_tpu.train.data import label_positions, playout_positions

pytestmark = pytest.mark.anyio


def test_playout_positions_shapes():
    positions = playout_positions(n_games=3, max_plies=20, seed=0)
    assert positions
    for fen, score in positions:
        assert score in (0.0, 0.5, 1.0)
        assert len(fen.split()) >= 4


async def test_label_and_train():
    service = SearchService(
        weights=NnueWeights.random(seed=0), pool_slots=64,
        batch_capacity=64, tt_bytes=8 << 20, backend="scalar",
    )
    try:
        positions = playout_positions(n_games=2, max_plies=16, seed=1)[:12]
        batch_np = await label_positions(service, positions, nodes=400)
    finally:
        service.close()

    n = batch_np["indices"].shape[0]
    assert n > 0
    assert batch_np["indices"].shape == (n, 2, 32)
    assert np.all(batch_np["indices"] <= spec.NUM_FEATURES)
    assert np.all(np.abs(batch_np["score_cp"]) <= 30000)
    assert set(np.unique(batch_np["outcome"])) <= {0.0, 0.5, 1.0}

    # The full-spec trainer consumes the batch directly.
    trainer = Trainer(cfg=NetConfig())
    state = trainer.init(seed=0)
    batch = {k: jnp.asarray(v) for k, v in batch_np.items()}
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
