"""A fake lichess fishnet server for integration tests.

Serves the JSON protocol documented in the reference's doc/protocol.md
(acquire / analysis / move / abort / status / key). The reference has no
such test double — SURVEY.md §4 calls out creating one as the first piece
of test infrastructure the new framework must add.

Queue semantics mimic lila: jobs are handed out on acquire, re-queued if
aborted, and recorded on submission. ``slow=true`` clients only get
system-queue jobs.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from aiohttp import web

VALID_KEY = "TESTKEY"


@dataclass
class FakeJob:
    body: dict
    user_queue: bool = True
    acquired_by: Optional[str] = None
    #: Monotonic time of the LAST handout (0 = never handed). Drives the
    #: server-side reassignment sweep (``FakeLichess.reassign_after``).
    last_handed: float = 0.0


@dataclass
class FleetUnit:
    """Per-work-unit audit record: every handout to any process, every
    completion, every time the server took it back."""

    #: (monotonic time, process key) for each handout.
    handouts: List = field(default_factory=list)
    completions: int = 0
    completed_by: Optional[str] = None
    #: Times the server re-queued it (client abort or timeout sweep).
    requeues: List = field(default_factory=list)  # (time, reason)
    #: Stale submissions the server refused (404): the sweep had
    #: already re-handed the unit to another process, or it was
    #: already completed. Fencing is what keeps completions exactly
    #: once when a partitioned-but-alive process's submit finally
    #: lands after its work was given away.
    fences: List = field(default_factory=list)  # (time, proc)


class FleetLedger:
    """Server-side exactly-once audit across PROCESSES — the cross-
    process twin of ``resilience/accounting.py`` (which lives inside one
    client and dies with it). Tracks every work unit the server ever
    handed to any process and answers, after kills / partitions /
    drains: was anything LOST (handed out, never completed, no longer
    queued for reassignment) or DUPLICATED (completed more than once)?

    Mutated only from the server's single event loop; readers take
    snapshots after the run.
    """

    def __init__(self) -> None:
        self.units: Dict[str, FleetUnit] = {}
        #: Successful-handout timestamps per process key — recovery-time
        #: measurement: first acquire after a restart marks the process
        #: back at steady state.
        self.acquires_by_proc: Dict[str, List[float]] = {}

    def record_handed(self, work_id: str, proc: str) -> None:
        now = time.monotonic()
        unit = self.units.setdefault(work_id, FleetUnit())
        unit.handouts.append((now, proc))
        self.acquires_by_proc.setdefault(proc, []).append(now)

    def record_completed(self, work_id: str, proc: str) -> None:
        unit = self.units.setdefault(work_id, FleetUnit())
        unit.completions += 1
        unit.completed_by = proc

    def record_fenced(self, work_id: str, proc: str) -> None:
        unit = self.units.setdefault(work_id, FleetUnit())
        unit.fences.append((time.monotonic(), proc))

    def record_requeued(self, work_id: str, reason: str) -> None:
        unit = self.units.setdefault(work_id, FleetUnit())
        unit.requeues.append((time.monotonic(), reason))

    def report(self, open_ids=()) -> Dict[str, object]:
        """The audit. ``open_ids``: work ids still queued on the server
        (awaiting reassignment) — handed-but-uncompleted units among
        them are in flight, not lost."""
        open_set = set(open_ids)
        handed = [w for w, u in self.units.items() if u.handouts]
        lost = [
            w for w, u in self.units.items()
            if u.handouts and u.completions == 0 and w not in open_set
        ]
        duplicated = [w for w, u in self.units.items() if u.completions > 1]
        reassigned = [w for w, u in self.units.items() if u.requeues]
        multi_proc = [
            w for w, u in self.units.items()
            if len({p for _, p in u.handouts}) > 1
        ]
        return {
            "handed": len(handed),
            "completed": sum(
                1 for u in self.units.values() if u.completions > 0
            ),
            "lost": sorted(lost),
            "duplicated": sorted(duplicated),
            "reassigned": len(reassigned),
            "fenced": sum(len(u.fences) for u in self.units.values()),
            "multi_proc": sorted(multi_proc),
            "clean": not lost and not duplicated,
        }

    def assert_clean(self, open_ids=()) -> None:
        report = self.report(open_ids)
        assert report["clean"], (
            f"fleet ledger dirty: lost={report['lost']} "
            f"duplicated={report['duplicated']}"
        )


@dataclass
class FakeLichess:
    """In-memory job queue + recorders, exposed over HTTP."""

    jobs: List[FakeJob] = field(default_factory=list)
    analyses: Dict[str, List[dict]] = field(default_factory=dict)
    #: How many times a COMPLETED analysis was received per work id —
    #: the server-side half of the exactly-once assertion (the
    #: ``analyses`` dict alone would silently hide duplicates).
    analysis_submission_counts: Dict[str, int] = field(default_factory=dict)
    progress_reports: Dict[str, List[dict]] = field(default_factory=dict)
    moves: Dict[str, dict] = field(default_factory=dict)
    aborted: List[str] = field(default_factory=list)
    acquire_count: int = 0
    reject_with: Optional[int] = None  # force an HTTP status on acquire
    #: Fail the next N completed-analysis submissions with HTTP 500
    #: (exercises the client's submit retry + circuit breaker).
    fail_submits: int = 0
    status_supported: bool = True
    abort_supported: bool = True
    require_key: bool = True
    #: Saturating load generator: keep at least this many unacquired
    #: system-queue analysis jobs in the queue at every acquire — the
    #: queue never drains, which is what "4x saturating load" means for
    #: the overload bench. 0 disables (default: finite queue as before).
    auto_refill: int = 0
    #: With auto_refill active, every Nth synthesized job is a best-move
    #: job so the latency lane sees traffic during saturation. 0 = never.
    refill_move_every: int = 0
    #: Cap on total synthesized jobs, so a shedding client can't make the
    #: generator spin forever. None = unbounded.
    refill_limit: Optional[int] = None
    refill_count: int = 0
    #: Latency bookkeeping (monotonic clock): when a job was handed out
    #: on acquire, when its first progress/analysis report arrived, when
    #: the completed analysis landed, and when a move was submitted.
    handed_at: Dict[str, float] = field(default_factory=dict)
    first_report_at: Dict[str, float] = field(default_factory=dict)
    completed_at: Dict[str, float] = field(default_factory=dict)
    move_done_at: Dict[str, float] = field(default_factory=dict)
    #: Generated work-id prefix. Override when one test (or soak phase)
    #: runs several servers against one shared ledger: each server's
    #: counter restarts at 0, so identical prefixes would collide.
    work_id_prefix: str = "wk"
    #: Cross-process exactly-once audit (cluster tests, bench --cluster).
    #: Always recorded — it is pure bookkeeping on existing handlers.
    fleet: FleetLedger = field(default_factory=FleetLedger)
    #: Server-side reassignment timeout (seconds): an acquired job not
    #: completed within this window goes back in the queue for another
    #: process — lila's recovery primitive (doc/protocol.md), and the
    #: only thing that rescues a SIGKILLed process's work. None = no
    #: sweep (single-process tests keep the old semantics).
    reassign_after: Optional[float] = None
    _counter: itertools.count = field(default_factory=itertools.count)

    # -- job injection (test side) ---------------------------------------

    def add_analysis_job(
        self,
        moves: str = "e2e4 e7e5",
        position: str = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        variant: str = "standard",
        skip_positions: Optional[List[int]] = None,
        nodes: int = 5000,
        game_id: Optional[str] = None,
        multipv: Optional[int] = None,
        depth: Optional[int] = None,
        user_queue: bool = False,
        work_id: Optional[str] = None,
    ) -> str:
        work_id = work_id or f"{self.work_id_prefix}{next(self._counter):06d}"
        work = {
            "type": "analysis",
            "id": work_id,
            "nodes": {"sf15": nodes, "sf14": nodes, "classical": nodes * 2},
            "timeout": 7000,
        }
        if multipv is not None:
            work["multipv"] = multipv
        if depth is not None:
            work["depth"] = depth
        body = {
            "work": work,
            "game_id": game_id or "",
            "position": position,
            "variant": variant,
            "moves": moves,
            "skipPositions": skip_positions or [],
        }
        self.jobs.append(FakeJob(body=body, user_queue=user_queue))
        return work_id

    def add_move_job(
        self,
        moves: str = "",
        position: str = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        level: int = 5,
        clock: Optional[dict] = None,
        variant: str = "standard",
        work_id: Optional[str] = None,
    ) -> str:
        work_id = work_id or f"{self.work_id_prefix}{next(self._counter):06d}"
        work: dict = {"type": "move", "id": work_id, "level": level}
        if clock:
            work["clock"] = clock
        body = {
            "work": work,
            "game_id": "",
            "position": position,
            "variant": variant,
            "moves": moves,
        }
        self.jobs.append(FakeJob(body=body, user_queue=False))
        return work_id

    def _refill(self) -> None:
        """Top the queue back up to ``auto_refill`` unacquired jobs."""
        if self.auto_refill <= 0:
            return
        pending = sum(1 for j in self.jobs if j.acquired_by is None)
        while pending < self.auto_refill:
            if self.refill_limit is not None and self.refill_count >= self.refill_limit:
                return
            self.refill_count += 1
            if (
                self.refill_move_every > 0
                and self.refill_count % self.refill_move_every == 0
            ):
                self.add_move_job()
            else:
                self.add_analysis_job()
            pending += 1

    # -- handlers --------------------------------------------------------

    def _check_auth(self, request: web.Request, body: Optional[dict]) -> bool:
        if not self.require_key:
            return True
        auth = request.headers.get("Authorization", "")
        if auth == f"Bearer {VALID_KEY}":
            return True
        if body and body.get("fishnet", {}).get("apikey") == VALID_KEY:
            return True
        return False

    def _reassign_stale(self) -> None:
        """The server-side reassignment sweep: acquired jobs older than
        ``reassign_after`` go back in the queue. Run at every acquire —
        the moment another process shows up hungry is exactly when a
        dead process's work should become available again."""
        if self.reassign_after is None:
            return
        now = time.monotonic()
        for job in self.jobs:
            if (
                job.acquired_by is not None
                and now - job.last_handed > self.reassign_after
            ):
                self.fleet.record_requeued(job.body["work"]["id"], "timeout")
                job.acquired_by = None

    async def handle_acquire(self, request: web.Request) -> web.Response:
        self.acquire_count += 1
        body = await request.json()
        if self.reject_with:
            return web.Response(status=self.reject_with, text="rejected by test")
        if not self._check_auth(request, body):
            return web.Response(status=401, text="unknown key")
        slow = request.query.get("slow") == "true"
        self._reassign_stale()
        self._refill()
        for job in self.jobs:
            if job.acquired_by is None and not (slow and job.user_queue):
                proc = body.get("fishnet", {}).get("apikey", "?")
                job.acquired_by = proc
                job.last_handed = time.monotonic()
                self.handed_at.setdefault(job.body["work"]["id"], time.monotonic())
                self.fleet.record_handed(job.body["work"]["id"], proc)
                return web.json_response(job.body, status=202)
        return web.Response(status=204)

    def _fence(self, work_id: str, body: dict) -> Optional[web.Response]:
        """Exactly-once enforcement: refuse (404, like lila for work it
        no longer knows) a completion from a process that is not the
        unit's CURRENT holder — the timeout sweep re-handed it, or it
        was already completed. Without this, a partitioned-but-alive
        process's delayed submit lands after the reassignee's and the
        unit double-completes. A requeued-but-unclaimed unit still
        accepts its original holder's late submit (the sweep was
        premature; nobody else did the work)."""
        proc = body.get("fishnet", {}).get("apikey", "?")
        job = next(
            (j for j in self.jobs if j.body["work"]["id"] == work_id), None
        )
        stale = (
            job is None
            if work_id in self.fleet.units
            else False
        ) or (
            job is not None
            and job.acquired_by is not None
            and job.acquired_by != proc
        )
        if stale:
            self.fleet.record_fenced(work_id, proc)
            return web.Response(status=404, text="unknown work")
        return None

    async def handle_analysis(self, request: web.Request) -> web.Response:
        work_id = request.match_info["id"]
        body = await request.json()
        if not self._check_auth(request, body):
            return web.Response(status=401)
        parts = body.get("analysis", [])
        self.first_report_at.setdefault(work_id, time.monotonic())
        # Lila quirk: a report whose first part is null is a progress
        # report, not a completed analysis (reference src/queue.rs:686-697).
        if parts and parts[0] is None:
            self.progress_reports.setdefault(work_id, []).append(body)
        else:
            fenced = self._fence(work_id, body)
            if fenced is not None:
                return fenced
            if self.fail_submits > 0:
                self.fail_submits -= 1
                return web.Response(status=500, text="injected submit failure")
            self.analysis_submission_counts[work_id] = (
                self.analysis_submission_counts.get(work_id, 0) + 1
            )
            self.analyses[work_id] = body
            self.completed_at.setdefault(work_id, time.monotonic())
            self.fleet.record_completed(
                work_id, body.get("fishnet", {}).get("apikey", "?")
            )
            self.jobs = [j for j in self.jobs if j.body["work"]["id"] != work_id]
        return web.Response(status=204)

    async def handle_move(self, request: web.Request) -> web.Response:
        work_id = request.match_info["id"]
        body = await request.json()
        if not self._check_auth(request, body):
            return web.Response(status=401)
        fenced = self._fence(work_id, body)
        if fenced is not None:
            return fenced
        self.moves[work_id] = body
        self.move_done_at.setdefault(work_id, time.monotonic())
        proc = body.get("fishnet", {}).get("apikey", "?")
        self.fleet.record_completed(work_id, proc)
        self.jobs = [j for j in self.jobs if j.body["work"]["id"] != work_id]
        # Chained acquire (202 with next job) when available.
        for job in self.jobs:
            if job.acquired_by is None and job.body["work"]["type"] == "move":
                job.acquired_by = proc
                job.last_handed = time.monotonic()
                self.handed_at.setdefault(job.body["work"]["id"], time.monotonic())
                self.fleet.record_handed(job.body["work"]["id"], proc)
                return web.json_response(job.body, status=202)
        return web.Response(status=204)

    async def handle_abort(self, request: web.Request) -> web.Response:
        if not self.abort_supported:
            return web.Response(status=404)
        work_id = request.match_info["id"]
        body = await request.json()
        if not self._check_auth(request, body):
            return web.Response(status=401)
        self.aborted.append(work_id)
        for job in self.jobs:
            if job.body["work"]["id"] == work_id:
                if job.acquired_by is not None:
                    self.fleet.record_requeued(work_id, "abort")
                job.acquired_by = None  # re-queue
        return web.Response(status=204)

    async def handle_status(self, request: web.Request) -> web.Response:
        if not self.status_supported:
            return web.Response(status=404)
        user = [j for j in self.jobs if j.user_queue and j.acquired_by is None]
        system = [j for j in self.jobs if not j.user_queue and j.acquired_by is None]
        return web.json_response(
            {
                "analysis": {
                    "user": {"acquired": 0, "queued": len(user), "oldest": 0},
                    "system": {"acquired": 0, "queued": len(system), "oldest": 0},
                }
            }
        )

    async def handle_key(self, request: web.Request) -> web.Response:
        auth = request.headers.get("Authorization", "")
        if auth == f"Bearer {VALID_KEY}":
            return web.Response(status=200)
        return web.Response(status=401)

    async def handle_key_legacy(self, request: web.Request) -> web.Response:
        if request.match_info["key"] == VALID_KEY:
            return web.Response(status=200)
        return web.Response(status=404)

    def fleet_report(self) -> Dict[str, object]:
        """The fleet-ledger audit, with still-queued jobs counted as in
        flight (awaiting reassignment), not lost."""
        open_ids = [j.body["work"]["id"] for j in self.jobs]
        return self.fleet.report(open_ids)

    def app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/fishnet/acquire", self.handle_acquire)
        app.router.add_post("/fishnet/analysis/{id}", self.handle_analysis)
        app.router.add_post("/fishnet/move/{id}", self.handle_move)
        app.router.add_post("/fishnet/abort/{id}", self.handle_abort)
        app.router.add_get("/fishnet/status", self.handle_status)
        app.router.add_get("/fishnet/key", self.handle_key)
        app.router.add_get("/fishnet/key/{key}", self.handle_key_legacy)
        return app


class FakeServer:
    """Async context manager running a FakeLichess on an ephemeral port."""

    def __init__(self, lichess: Optional[FakeLichess] = None) -> None:
        self.lichess = lichess or FakeLichess()
        self.endpoint = ""
        self._runner: Optional[web.AppRunner] = None

    async def __aenter__(self) -> "FakeServer":
        self._runner = web.AppRunner(self.lichess.app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        self.endpoint = f"http://127.0.0.1:{port}/fishnet"
        return self

    async def __aexit__(self, *exc) -> None:
        if self._runner:
            await self._runner.cleanup()
