"""Sanitizer smoke (slow, not tier-1): build the ASan+UBSan pool stress
driver and run it — including the persistent-anchor provide-guard unit
phase — failing on any sanitizer report.

Tier-1 proves the pool's results are right; this job is the only gate
that can see a data race or heap error that happens to produce the right
move.  TSan is covered by `tools/sanitize.sh tsan` / CI, not here: its
runtime roughly 10x's the stress wall clock.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def stress_net(tmp_path_factory) -> Path:
    from fishnet_tpu.nnue.weights import NnueWeights

    path = tmp_path_factory.mktemp("san") / "stress.nnue"
    NnueWeights.random(seed=3).save(path)
    return path


@pytest.mark.parametrize("sanitizer", ["asan", "ubsan"])
def test_pool_stress_clean_under_sanitizer(sanitizer, stress_net):
    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    build = subprocess.run(
        ["make", "-C", str(REPO / "cpp"), sanitizer],
        capture_output=True,
        text=True,
    )
    assert build.returncode == 0, build.stderr[-2000:]
    binary = REPO / "cpp" / "build" / sanitizer / "pool_stress_main"
    assert binary.exists()
    env = dict(
        os.environ,
        ASAN_OPTIONS="halt_on_error=1:detect_leaks=0",
        UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1",
    )
    run = subprocess.run(
        [str(binary), str(stress_net), "12", "2"],
        capture_output=True,
        text=True,
        env=env,
        timeout=600,
    )
    assert run.returncode == 0, (run.stdout + run.stderr)[-4000:]
    # The guard phase must actually have executed (needs the net).
    assert "provide-guard: full-provide contract enforced" in run.stdout
