"""Resilience subsystem (doc/resilience.md): the deterministic fault
plane, the exactly-once batch ledger, requeue-with-cap and the deadline
flush in the scheduler, the submit circuit breaker, and the
degradation ladder — including bit-identical analysis output at every
rung, forced through real fault plans."""

import asyncio
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
from fake_server import VALID_KEY, FakeServer  # noqa: E402
from test_client_e2e import make_client, wait_for  # noqa: E402

from fishnet_tpu.client import Client
from fishnet_tpu.engine.mock import MockEngineFactory
from fishnet_tpu.net import api as api_mod
from fishnet_tpu.resilience import accounting, faults
from fishnet_tpu.resilience.accounting import BatchLedger, LedgerViolation
from fishnet_tpu.resilience.faults import (
    FaultCrash,
    FaultInjected,
    FaultPlan,
    FaultPlanError,
)
from fishnet_tpu.resilience.supervisor import (
    RUNGS,
    CircuitBreaker,
    RespawnBudgetExhausted,
    ServiceSupervisor,
)
from fishnet_tpu.sched import queue as queue_mod
from fishnet_tpu.utils.logger import Logger

pytestmark = pytest.mark.anyio


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    yield
    faults.clear()
    accounting.clear()


# -- fault plane ----------------------------------------------------------


def test_fault_plan_parsing():
    plan = FaultPlan.parse(
        "seed=42; net.acquire:nth=2..3:error; net.submit:every=4:latency=0.5;"
        "service.device_step:p=0.25:crash; engine.spawn:nth=1:hang=2"
    )
    assert plan.seed == 42
    rules = plan.rules
    assert rules["net.acquire"][0].lo == 2 and rules["net.acquire"][0].hi == 3
    assert rules["net.submit"][0].trigger == "every"
    assert rules["net.submit"][0].arg == 0.5
    assert rules["service.device_step"][0].prob == 0.25
    assert rules["engine.spawn"][0].action == "hang"


@pytest.mark.parametrize(
    "bad",
    [
        "nosuch.site:nth=1:error",
        "net.acquire:nth=0:error",
        "net.acquire:nth=3..2:error",
        "net.acquire:wat=1:error",
        "net.acquire:nth=1:explode",
        "net.acquire:p=1.5:error",
        "net.acquire:nth=1",
        "seed=banana",
        "net.acquire:nth=1:latency=-1",
    ],
)
def test_fault_plan_rejects_bad_specs(bad):
    with pytest.raises(FaultPlanError):
        FaultPlan.parse(bad)


def test_nth_trigger_is_deterministic():
    faults.install("net.acquire:nth=3:error")
    faults.fire("net.acquire")
    faults.fire("net.acquire")
    with pytest.raises(FaultInjected) as err:
        faults.fire("net.acquire")
    assert err.value.site == "net.acquire"
    faults.fire("net.acquire")  # past the window: clean again
    assert faults.current().counts()["net.acquire"] == 4


def test_probability_trigger_is_seeded():
    def decisions(seed):
        plan = FaultPlan.parse(f"seed={seed};queue.schedule:p=0.5:error")
        return [plan.poll("queue.schedule") is not None for _ in range(32)]

    assert decisions(7) == decisions(7)  # same seed, same faults
    assert decisions(7) != decisions(8)  # different seed, different faults


def test_actions_latency_hang_crash():
    faults.install(
        "net.submit:nth=1:latency=0.05;net.submit:nth=2:hang=0.05;"
        "net.submit:nth=3:crash"
    )
    t0 = time.monotonic()
    faults.fire("net.submit")  # latency: sleeps, proceeds
    assert time.monotonic() - t0 >= 0.05
    with pytest.raises(FaultInjected):
        faults.fire("net.submit")  # hang: sleeps then raises
    with pytest.raises(FaultCrash):
        faults.fire("net.submit")
    assert issubclass(FaultCrash, FaultInjected)


async def test_fire_async_in_event_loop():
    faults.install("net.acquire:nth=1:error")
    with pytest.raises(FaultInjected):
        await faults.fire_async("net.acquire")


def test_disabled_plane_is_inert():
    assert not faults.enabled()
    faults.fire("net.acquire")  # no plan: a no-op, never raises


# -- batch ledger ---------------------------------------------------------


def test_ledger_clean_lifecycle():
    led = BatchLedger()
    led.record_acquired("b1")
    led.record_scheduled("b1")
    led.record_stepped("b1")
    led.record_requeued("b1", 1)
    led.record_submitted("b1")
    rep = led.assert_clean()
    assert rep["submitted"] == 1 and rep["requeues"] == 1


def test_ledger_flags_lost_and_duplicated():
    led = BatchLedger()
    led.record_acquired("lost1")
    with pytest.raises(LedgerViolation):
        led.assert_clean()
    led.record_abandoned("lost1", "test")
    led.assert_clean()

    led.record_acquired("dup1")
    led.record_submitted("dup1")
    led.record_submitted("dup1")
    with pytest.raises(LedgerViolation):
        led.assert_clean()
    assert led.report()["duplicated"] == ["dup1"]


def test_ledger_reacquire_after_abandon_is_fresh_lifecycle():
    led = BatchLedger()
    led.record_acquired("b1")
    led.record_abandoned("b1", "requeue_cap")
    led.record_acquired("b1")  # server reassigned it to us again
    led.record_submitted("b1")
    rep = led.assert_clean()
    assert rep["submitted"] == 1


# -- circuit breaker ------------------------------------------------------


def test_breaker_state_machine():
    now = [0.0]
    b = CircuitBreaker(
        failure_threshold=2, cooldown_seconds=10.0, clock=lambda: now[0]
    )
    assert b.allow() and b.state == b.CLOSED
    assert not b.record_failure()
    assert b.record_failure()  # threshold reached: OPEN
    assert b.state == b.OPEN and not b.allow()
    assert b.remaining_cooldown() == 10.0
    now[0] = 10.5
    assert b.allow() and b.state == b.HALF_OPEN  # the probe
    assert not b.allow()  # only one probe at a time
    assert b.record_failure()  # failed probe: straight back to OPEN
    assert b.state == b.OPEN
    now[0] = 21.0
    assert b.allow()
    assert b.record_success()  # closed: caller drains parked work
    assert b.state == b.CLOSED and b.allow()


# -- supervisor ladder ----------------------------------------------------


class _FakeService:
    def __init__(self, rung):
        self.psqt_path = rung or "xla"
        self.failure_listener = None


def test_supervisor_degrades_down_the_lattice():
    built = []

    def builder(rung):
        svc = _FakeService(rung)
        built.append(rung)
        return svc

    sup = ServiceSupervisor(
        builder, degrade_after=2, healthy_seconds=3600, logger=Logger()
    )
    svc = sup.build()
    assert built == [None]  # first build: auto-select
    assert sup.rung == "xla"  # aligned to the realized path
    assert svc.failure_listener == sup.note_failure
    sup.build()  # death 1: respawn, same rung
    assert built[-1] is None
    sup.build()  # death 2: degrade
    assert built[-1] == "host-material"
    assert sup.rung == "host-material"
    sup.build()
    sup.build()  # already at the bottom: stays there
    assert built[-1] == "host-material"
    assert sup.respawns == 4


def test_supervisor_respawn_budget():
    sup = ServiceSupervisor(
        lambda rung: _FakeService(rung), degrade_after=10,
        max_respawns=2, respawn_window=3600, healthy_seconds=3600,
    )
    sup.build()
    sup.build()
    sup.build()
    with pytest.raises(RespawnBudgetExhausted):
        sup.build()


def test_supervisor_start_rung_and_rungs_constant():
    assert RUNGS == ("fused", "xla", "host-material")
    sup = ServiceSupervisor(lambda rung: _FakeService(rung), start_rung="xla")
    sup.build()
    assert sup.rung == "xla"
    with pytest.raises(ValueError):
        ServiceSupervisor(lambda rung: None, start_rung="warp-drive")


# -- client e2e under fault plans ----------------------------------------


async def test_acquire_faults_retry_and_ledger_clean():
    faults.install("net.acquire:nth=1..2:error")
    led = accounting.install()
    async with FakeServer() as server:
        job = server.lichess.add_analysis_job(moves="e2e4")
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(lambda: job in server.lichess.analyses)
        await client.stop(abort_pending=False)
    led.assert_clean()
    assert led.record(job).terminal == "submitted"
    assert faults.current().counts()["net.acquire"] >= 3


async def test_spawn_fault_requeues_preserving_acquire_order():
    base_requeued = queue_mod._REQUEUED.value()
    faults.install("engine.spawn:nth=1:error")
    led = accounting.install()
    async with FakeServer() as server:
        first = server.lichess.add_analysis_job(moves="e2e4")
        second = server.lichess.add_analysis_job(moves="d2d4")
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(
            lambda: first in server.lichess.analyses
            and second in server.lichess.analyses
        )
        await client.stop(abort_pending=False)
        # The failed position was requeued at the FRONT: the first-
        # acquired batch still finishes first, not starved behind the
        # fresh batch (submission order == acquire order).
        order = list(server.lichess.analyses)
        assert order.index(first) < order.index(second)
        assert server.lichess.analysis_submission_counts[first] == 1
    assert queue_mod._REQUEUED.value() - base_requeued >= 1
    rep = led.assert_clean()
    assert rep["requeues"] >= 1


async def test_requeue_generation_cap_abandons():
    # A deterministically-failing position must not retry forever: after
    # MAX_REQUEUE_GENERATIONS the batch is abandoned to the server's
    # reassignment timeout (and accounted, not lost).
    led = accounting.install()
    async with FakeServer() as server:
        doomed = server.lichess.add_analysis_job(moves="e2e4 e7e5 g1f3")
        survivor = server.lichess.add_analysis_job(moves="d2d4")
        factory = MockEngineFactory(fail_on="#3")
        client = make_client(server.endpoint, cores=1, engine_factory=factory)
        await client.start()
        assert await wait_for(lambda: survivor in server.lichess.analyses)
        assert await wait_for(
            lambda: (led.record(doomed) or None) is not None
            and led.record(doomed).terminal == "abandoned"
        )
        await client.stop(abort_pending=False)
        assert doomed not in server.lichess.analyses
        assert doomed not in server.lichess.aborted  # silent, like the reference
    rec = led.record(doomed)
    assert rec.requeues == queue_mod.MAX_REQUEUE_GENERATIONS
    led.assert_clean()


async def test_deadline_flushes_partial_analysis():
    led = accounting.install()
    async with FakeServer() as server:
        job = server.lichess.add_analysis_job(moves="e2e4 e7e5")
        factory = MockEngineFactory(hang_on="#1")  # ply 1 hangs forever
        client = make_client(
            server.endpoint, cores=2, engine_factory=factory,
            batch_deadline=1.0,
        )
        await client.start()
        assert await wait_for(lambda: job in server.lichess.analyses, timeout=20)
        body = server.lichess.analyses[job]
        await client.stop(abort_pending=True)
    parts = body["analysis"]
    assert len(parts) == 3
    assert parts[1] == {"skipped": True}  # the hung ply, flushed as skipped
    assert parts[0] is not None and parts[2] is not None
    assert server.lichess.analysis_submission_counts[job] == 1
    rec = led.record(job)
    assert rec.flushed and rec.terminal == "submitted"
    led.assert_clean()


async def test_submit_failures_open_breaker_then_recover(monkeypatch):
    monkeypatch.setenv(api_mod.BREAKER_THRESHOLD_ENV, "2")
    monkeypatch.setenv(api_mod.BREAKER_COOLDOWN_ENV, "0.3")
    base_retries = api_mod._SUBMIT_RETRIES.value()
    led = accounting.install()
    async with FakeServer() as server:
        server.lichess.fail_submits = 2  # HTTP 500 on the first two finals
        jobs = [
            server.lichess.add_analysis_job(moves=m)
            for m in ("e2e4", "d2d4", "g1f3")
        ]
        client = make_client(server.endpoint, cores=2)
        await client.start()
        assert await wait_for(
            lambda: all(j in server.lichess.analyses for j in jobs),
            timeout=30,
        )
        await client.stop(abort_pending=False)
        counts = server.lichess.analysis_submission_counts
        assert all(counts[j] == 1 for j in jobs)  # exactly once, each
    assert api_mod._SUBMIT_RETRIES.value() - base_retries >= 1
    led.assert_clean()
    # Breaker closed again after recovery (gauge exports 0).
    from fishnet_tpu.resilience.supervisor import _BREAKER_STATE

    assert _BREAKER_STATE.labels(endpoint="submit").value == 0


# -- degradation ladder: bit-identical output at every rung ---------------


_LADDER_FENS = (
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
    "8/2k5/3p4/p2P1p2/P2P1P2/8/8/4K3 w - - 0 1",
)


async def _rung_results(svc):
    svc.set_prefetch(0, adaptive=False)  # deterministic TT evolution
    out = []
    for fen in _LADDER_FENS:
        r = await svc.search(fen, [], depth=1)
        line = [l for l in r.lines if l.multipv == 1][-1]
        out.append((fen, line.value, line.is_mate, r.best_move, r.nodes))
    return out


async def test_ladder_transitions_forced_by_fault_plans_are_bit_identical():
    """Satellite 3: step fused -> xla -> host-material through REAL
    device_step crash faults (supervisor + factory recovery path) and
    pin bit-identical analysis output at every rung — degradation
    trades efficiency, never correctness. Reuses the PR 2 parity
    surface: the fused rung realizes the Pallas kernel in interpreter
    mode on CPU, exactly like tests/test_ops.py."""
    from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.protocol.types import EngineFlavor
    from fishnet_tpu.search.service import SearchService

    weights = NnueWeights.random(seed=21)  # the parity-suite net

    def builder(rung):
        return SearchService(
            weights=weights, pool_slots=16, batch_capacity=64,
            tt_bytes=8 << 20, backend="jax", psqt_path=rung,
        )

    sup = ServiceSupervisor(
        builder, start_rung="fused", degrade_after=1, logger=Logger()
    )
    factory = TpuNnueEngineFactory(service_builder=sup.build)
    results = {}
    try:
        for expected in ("fused", "xla", "host-material"):
            engine = await factory.create(EngineFlavor.OFFICIAL)
            assert engine.service.psqt_path == expected
            results[expected] = await _rung_results(engine.service)
            if expected != "host-material":
                # Crash the device path on a FRESH position (a repeat
                # would be answered from the TT without any dispatch);
                # the next create() respawns one rung down
                # (degrade_after=1).
                faults.install("service.device_step:nth=1:crash")
                with pytest.raises(Exception):
                    await engine.service.search(
                        "rnbqkb1r/pppppppp/5n2/8/3P4/8/PPP1PPPP/RNBQKBNR w KQkq - 1 2",
                        [], depth=3,
                    )
                faults.clear()
    finally:
        factory.close()
    assert results["fused"] == results["xla"] == results["host-material"], (
        results
    )
    assert sup.rung == "host-material" and sup.respawns == 2
