"""The UCI server front-end, driven as a real subprocess over pipes."""

import asyncio
import os
import sys

import pytest

pytestmark = pytest.mark.anyio

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
}
ENV.pop("PALLAS_AXON_POOL_IPS", None)


async def drive(commands, patterns, timeout=120):
    """Send commands; collect output until all patterns appear (in order)."""
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "fishnet_tpu", "uci",
        "--no-conf", "--no-stats-file", "--microbatch", "64",
        env=ENV,
        stdin=asyncio.subprocess.PIPE,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.DEVNULL,
    )
    try:
        proc.stdin.write(("\n".join(commands) + "\n").encode())
        await proc.stdin.drain()
        lines = []
        remaining = list(patterns)

        async def read():
            while remaining:
                raw = await proc.stdout.readline()
                if not raw:
                    break
                line = raw.decode().strip()
                lines.append(line)
                if remaining and remaining[0] in line:
                    remaining.pop(0)

        await asyncio.wait_for(read(), timeout)
        assert not remaining, f"missing {remaining!r} in output:\n" + "\n".join(lines)
        return lines
    finally:
        try:
            proc.stdin.write(b"quit\n")
            await proc.stdin.drain()
            await asyncio.wait_for(proc.wait(), 15)
        except Exception:  # noqa: BLE001
            try:
                proc.kill()
            except ProcessLookupError:
                pass


async def test_uci_handshake_and_mate():
    lines = await drive(
        [
            "uci",
            "isready",
            "position fen 6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
            "go depth 4",
        ],
        ["uciok", "readyok", "bestmove d1d8"],
    )
    assert any("id name fishnet-tpu" in l for l in lines)
    assert any("score mate 1" in l for l in lines)


async def test_uci_position_moves_and_nodes():
    lines = await drive(
        [
            "uci",
            "position startpos moves e2e4 e7e5",
            "go nodes 3000",
        ],
        ["uciok", "bestmove"],
    )
    infos = [l for l in lines if l.startswith("info depth")]
    assert infos and all("pv" in l for l in infos)


async def test_uci_variant_option():
    await drive(
        [
            "uci",
            "setoption name UCI_Variant value kingofthehill",
            "position fen 4k3/8/8/8/8/4K3/8/8 w - - 0 1",
            "go depth 4",
        ],
        ["uciok", "bestmove e3"],
    )


async def test_uci_clock_maps_to_movetime():
    # wtime/btime must bound the search (no depth-12 default ignoring the
    # clock): 2 s clocks -> ~50ms+ movetime, finishes well within timeout.
    await drive(
        [
            "uci",
            "position startpos moves e2e4",
            "go wtime 2000 btime 2000 winc 0 binc 0",
        ],
        ["uciok", "bestmove"],
        timeout=60,
    )


async def test_uci_malformed_go_is_ignored_not_fatal():
    await drive(
        [
            "uci",
            "position startpos",
            "go depth x movetime abc nodes 800",  # malformed tokens ignored
        ],
        ["uciok", "bestmove"],
    )


async def test_uci_second_go_supersedes_infinite():
    await drive(
        [
            "uci",
            "position startpos",
            "go infinite",
            "go depth 3",  # must cancel the infinite search, not hang
        ],
        ["uciok", "bestmove"],
        timeout=120,
    )


async def test_uci_stop_infinite():
    await drive(
        [
            "uci",
            "position startpos",
            "go infinite",
            "stop",
        ],
        ["uciok", "bestmove"],
        timeout=150,
    )
