"""Shared-plane batched MCTS (ISSUE 14): plane-vs-legacy bit parity on
every degradation rung, pre-wire AZ eval reuse, the preallocated step
buffer, collision/terminal/multipv tree semantics, self-play parity
plane-on vs plane-off, the tree-side telemetry families, and the
--mcts bench schema."""

import numpy as np
import pytest

import jax

from fishnet_tpu import telemetry
from fishnet_tpu.chess.board import Board
from fishnet_tpu.models.az import AzConfig, init_az_params
from fishnet_tpu.models.az_encoding import POLICY_SIZE
from fishnet_tpu.search import eval_cache
from fishnet_tpu.search.mcts import MctsConfig, MctsPool
from fishnet_tpu.telemetry.registry import REGISTRY
from fishnet_tpu.telemetry.spans import RECORDER

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
TINY = AzConfig(channels=16, blocks=2, value_hidden=16)

OPENINGS = [
    [], ["e2e4"], ["d2d4"], ["g1f3"],
    ["e2e4", "c7c5"], ["e2e4", "e7e5"], ["d2d4", "d7d5"],
    ["d2d4", "g8f6"],
]


@pytest.fixture(scope="module")
def params():
    return init_az_params(jax.random.PRNGKey(3), TINY)


class _CountingEval:
    """Instant uniform-policy evaluator (no jax): pins pure tree
    semantics independent of any dispatch path."""

    def __init__(self):
        self.calls = 0
        self.rows = 0

    def warmup(self, cap):
        pass

    def evaluate(self, planes_u8, n, keys=None):
        self.calls += 1
        self.rows += n
        return (
            np.zeros((n, POLICY_SIZE), np.float32),
            np.zeros(n, np.float32),
        )

    def close(self):
        pass


def _run_workload(pool, visits=80, trees=8):
    sids = [
        pool.submit(STARTPOS, list(OPENINGS[i % len(OPENINGS)]), visits)
        for i in range(trees)
    ]
    while pool.active() > 0:
        pool.step()
    out = []
    for sid in sids:
        r = pool.harvest(sid)
        out.append((r.best_move, r.visits, r.value,
                    tuple(r.root_visits), tuple(r.pv)))
    return out


# -- parity: legacy vs plane, every rung, escape hatch ----------------------


def _parity_run(params, monkeypatch, force_rung=None, legacy=False):
    eval_cache.reset_cache()
    cfg = MctsConfig(batch_capacity=64, az=TINY)
    plane = None
    if legacy:
        monkeypatch.setenv("FISHNET_NO_SHARED_AZ_PLANE", "1")
    else:
        monkeypatch.delenv("FISHNET_NO_SHARED_AZ_PLANE", raising=False)
        if force_rung is not None:
            from fishnet_tpu.search.az_plane import AzDispatchPlane

            plane = AzDispatchPlane(params, cfg, force_rung=force_rung)
    pool = MctsPool(params, cfg, evaluator=plane)
    try:
        return _run_workload(pool)
    finally:
        pool.close()
        if plane is not None:
            plane.close()


def test_plane_parity_all_rungs_and_hatch(params, monkeypatch):
    """The escape hatch restores the legacy path, and the shared plane
    matches it bit-for-bit on every forced degradation rung — with the
    AZ eval cache live (pre-wire hits interleave with dispatches)."""
    legacy = _parity_run(params, monkeypatch, legacy=True)
    assert any(r[1] > 0 for r in legacy)
    for rung in (None, 0, 1, 2):  # default ladder + each forced rung
        assert _parity_run(params, monkeypatch, force_rung=rung) == legacy


def test_az_prewire_warm_replay(params, monkeypatch):
    """A respawned pool (fresh memo) against the surviving process
    AzEvalCache resolves its leaves PRE-WIRE: nonzero prewire hits, and
    the registry family carries scope=prewire, family=az."""
    monkeypatch.delenv("FISHNET_NO_SHARED_AZ_PLANE", raising=False)
    cfg = MctsConfig(batch_capacity=64, az=TINY)
    cold_pool = MctsPool(params, cfg)
    cold = _run_workload(cold_pool)
    cold_counters = cold_pool.counters()["dispatch"]
    cold_pool.close()
    assert cold_counters["rows_dispatched"] > 0
    assert cold_counters["prewire_hits"] == 0

    warm_pool = MctsPool(params, cfg)  # fresh pool, fresh plane, warm cache
    warm = _run_workload(warm_pool)
    warm_counters = warm_pool.counters()["dispatch"]
    # Collect while the plane is live: close() unregisters its collector.
    hits = [
        s for fam in REGISTRY.collect()
        if fam.name == "fishnet_eval_cache_hits_total"
        for s in fam.samples
        if s.labels.get("scope") == "prewire"
        and s.labels.get("family") == "az"
    ]
    warm_pool.close()
    assert warm == cold  # cache payload round-trips exactly
    assert warm_counters["prewire_hits"] > 0
    assert warm_counters["rows_dispatched"] < cold_counters["rows_dispatched"]
    assert hits and sum(s.value for s in hits) > 0


def test_az_fingerprint_keys_nets_apart(params):
    """Cache keys are salted by the net fingerprint, so two different
    AZ nets (and the NNUE cache) can never serve each other's entries."""
    other = init_az_params(jax.random.PRNGKey(9), TINY)
    fp_a = eval_cache.az_net_fingerprint(params)
    fp_b = eval_cache.az_net_fingerprint(other)
    assert fp_a != fp_b
    # Same net hashes stably across calls.
    assert fp_a == eval_cache.az_net_fingerprint(params)
    key = eval_cache.az_position_key(0x1234ABCD, 7)
    assert (key ^ fp_a) != (key ^ fp_b)
    # Halfmove clock is part of the position identity (plane 17).
    assert eval_cache.az_position_key(0x1234ABCD, 7) != \
        eval_cache.az_position_key(0x1234ABCD, 8)


# -- satellite: preallocated step buffer ------------------------------------


def test_step_reuses_preallocated_batch_buffer(monkeypatch):
    """MctsPool.step must never allocate a fresh full-capacity
    (cap, 8, 8, 19) batch per step (the old zero-fill regression)."""
    cfg = MctsConfig(batch_capacity=128, az=TINY)
    pool = MctsPool({}, cfg, evaluator=_CountingEval())
    sids = [pool.submit(STARTPOS, [], 40) for _ in range(4)]
    full_allocs = []
    real_zeros = np.zeros

    def spy(shape, *a, **k):
        if (
            isinstance(shape, tuple) and len(shape) == 4
            and shape[0] == cfg.batch_capacity
        ):
            full_allocs.append(shape)
        return real_zeros(shape, *a, **k)

    monkeypatch.setattr(np, "zeros", spy)
    while pool.active() > 0:
        pool.step()
    monkeypatch.setattr(np, "zeros", real_zeros)
    for sid in sids:
        assert pool.harvest(sid).visits == 40
    pool.close()
    assert full_allocs == []


# -- tree semantics ---------------------------------------------------------


def test_collision_release_under_forced_line():
    """A single-legal-move root funnels every speculative walk onto one
    edge: the excess walks must collide, release their virtual loss
    completely, and still let the search finish its exact budget."""
    # White king boxed in by Qc2 (a2/b1/b2 covered, a1 not attacked —
    # no check, no capture): h3h4 is the single legal move, and unlike
    # a queen capture it leads to a live position, so the pending-leaf
    # window actually exists for the follow-up walks to collide in.
    forced = "4k3/8/8/8/8/7P/2q5/K7 w - - 0 1"
    assert Board(forced).legal_moves() == ["h3h4"]
    cfg = MctsConfig(
        batch_capacity=32, leaves_per_step=8, adaptive_leaves=False,
        az=TINY,
    )
    pool = MctsPool({}, cfg, evaluator=_CountingEval())
    sid = pool.submit(forced, [], 30)
    search = pool._searches[sid]
    while pool.active() > 0:
        pool.step()
    r = pool.harvest(sid)
    pool.close()
    assert r.best_move == "h3h4"
    assert r.visits == 30
    assert search.collisions > 0
    for node in search.nodes:
        assert not node.vloss.any()  # every walk's loss released


def test_terminal_leaf_backup_sign():
    """A mate found at a leaf backs up as a WIN for the side delivering
    it: the mating edge's total value equals its visit count exactly."""
    fen = "6k1/8/6K1/8/8/8/8/R7 w - - 0 1"  # Ra8# available
    cfg = MctsConfig(batch_capacity=32, az=TINY)
    pool = MctsPool({}, cfg, evaluator=_CountingEval())
    sid = pool.submit(fen, [], 200)
    search = pool._searches[sid]
    while pool.active() > 0:
        pool.step()
    r = pool.harvest(sid)
    pool.close()
    assert r.best_move == "a1a8"
    root = search.nodes[0]
    edge = root.moves.index("a1a8")
    assert root.n[edge] > 0
    # Each backup through the mate is -(terminal -1) == +1 at the root.
    assert root.w[edge] == root.n[edge]
    assert r.value == 1.0


def test_multipv_ranking_at_zero_visits():
    """Harvesting before the first backup must rank lines by policy
    prior (not move-generation order)."""
    cfg = MctsConfig(batch_capacity=32, az=TINY)
    pool = MctsPool({}, cfg, evaluator=_CountingEval())
    sid = pool.submit(STARTPOS, [], 500, multipv=3)
    search = pool._searches[sid]
    pool.step()  # root eval only; no simulation has completed yet
    pool.stop_search(sid)
    r = pool.harvest(sid)
    pool.close()
    root = search.nodes[0]
    assert int(root.n.sum()) == 0
    expected = [
        root.moves[i] for i in np.lexsort((root.priors, root.n))[::-1][:3]
    ]
    assert [line.move for line in r.lines] == expected


# -- self-play parity -------------------------------------------------------


def test_selfplay_bit_identical_plane_on_off(params, monkeypatch):
    from fishnet_tpu.train.selfplay import SelfPlayConfig, play_games

    def one(plane_off):
        if plane_off:
            monkeypatch.setenv("FISHNET_NO_SHARED_AZ_PLANE", "1")
        else:
            monkeypatch.delenv("FISHNET_NO_SHARED_AZ_PLANE", raising=False)
        eval_cache.reset_cache()
        pool = MctsPool(params, MctsConfig(batch_capacity=32, az=TINY))
        games = play_games(
            pool, SelfPlayConfig(games=2, visits=16, max_plies=6), seed=5
        )
        pool.close()
        return [
            (g.moves, g.outcome_white,
             [(rec.policy.tobytes(), rec.stm_white) for rec in g.records])
            for g in games
        ]

    assert one(plane_off=True) == one(plane_off=False)


# -- telemetry --------------------------------------------------------------


def test_mcts_telemetry_families_and_collect_span():
    telemetry.enable()
    try:
        cfg = MctsConfig(batch_capacity=32, az=TINY)
        pool = MctsPool({}, cfg, evaluator=_CountingEval())
        sids = [pool.submit(STARTPOS, [], 25) for _ in range(3)]
        while pool.active() > 0:
            pool.step()
        for sid in sids:
            pool.harvest(sid)
        fams = {f.name: f for f in REGISTRY.collect()}
        for name in (
            "fishnet_mcts_visits_total",
            "fishnet_mcts_collisions_total",
            "fishnet_mcts_subtree_reuse_total",
            "fishnet_mcts_batch_fill_ratio",
            "fishnet_mcts_trees_active",
        ):
            assert name in fams, name
        assert sum(
            s.value for s in fams["fishnet_mcts_visits_total"].samples
        ) >= 75
        assert "mcts_collect" in RECORDER.stages_seen()
        pool.close()
    finally:
        telemetry.disable()


# -- bench schema -----------------------------------------------------------


def test_bench_mcts_summary_schema():
    import bench

    phase = {k: 0 for k in bench.SUMMARY_SCHEMA["mcts.phase"]}
    summary = {k: 0 for k in bench.SUMMARY_SCHEMA["mcts"]}
    summary["mode"] = "mcts"
    for ph in ("baseline", "cold", "warm", "respawn"):
        summary[ph] = dict(phase)
    bench.validate_summary(summary)  # complete: must not raise
    del summary["warm"]["collision_rate"]
    with pytest.raises(ValueError):
        bench.validate_summary(summary)
