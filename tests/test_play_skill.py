"""Play-job behavioral parity: native skill weakening and clock-derived
think time (reference api.rs:222-273, stockfish.rs:254-344).

The reference weakens play jobs by setting the engine's `Skill Level`
(−9..20), which samples the played move among near-best lines; analysis
always runs at 20. It also forwards wtime/btime/winc/binc so the
engine's time manager can cut the level movetime short on a low clock.
Both behaviors live natively here (cpp/src/search.cpp skill pick,
engine/tpu_engine.py clock allocation) — these tests pin them.
"""

import time

import pytest

from fishnet_tpu.chess import Board
from fishnet_tpu.engine.tpu_engine import (
    TpuNnueEngine,
    _white_to_move,
    clock_movetime_seconds,
)
from fishnet_tpu.ipc import Position
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.protocol.types import (
    Clock,
    EngineFlavor,
    SkillLevel,
    Variant,
    Work,
)
from fishnet_tpu.search.service import SearchService
from tests.test_search import material_net

pytestmark = pytest.mark.anyio

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"

# Varied, quiet openings so the self-play match isn't eight copies of
# one game (the skill pick is deterministic per position+nodes).
OPENINGS = [
    ["e2e4", "e7e5"],
    ["d2d4", "d7d5"],
    ["c2c4", "e7e5"],
    ["g1f3", "d7d5"],
    ["e2e4", "c7c5"],
    ["d2d4", "g8f6"],
    ["e2e4", "e7e6"],
    ["c2c4", "c7c5"],
]

_PIECE_CP = {"p": 100, "n": 300, "b": 310, "r": 500, "q": 900, "k": 0}


def _material_white_cp(fen: str) -> int:
    total = 0
    for ch in fen.split()[0]:
        lo = ch.lower()
        if lo in _PIECE_CP:
            v = _PIECE_CP[lo]
            total += v if ch.isupper() else -v
    return total


@pytest.fixture(scope="module")
def service():
    svc = SearchService(
        weights=material_net(),
        pool_slots=16,
        batch_capacity=64,
        tt_bytes=16 << 20,
        backend="scalar",
    )
    yield svc
    svc.close()


def test_white_to_move_helper():
    assert _white_to_move(STARTPOS, [])
    assert not _white_to_move(STARTPOS, ["e2e4"])
    black_fen = STARTPOS.replace(" w ", " b ")
    assert not _white_to_move(black_fen, [])
    assert _white_to_move(black_fen, ["e7e5"])


def test_clock_movetime_allocation():
    # 60 s + 2 s inc: 60000/40 + 1500 = 3.0 s, under the half-clock cap.
    c = Clock(wtime_centis=6000, btime_centis=500, inc_seconds=2)
    assert clock_movetime_seconds(c, True) == pytest.approx(3.0)
    # Black at 5 s: 125 ms + 1500 ms = 1.625 s, under the 2.5 s cap.
    assert clock_movetime_seconds(c, False) == pytest.approx(1.625)
    # Near-flag: the 10 ms floor still produces a move.
    tiny = Clock(wtime_centis=1, btime_centis=1, inc_seconds=0)
    assert clock_movetime_seconds(tiny, True) == pytest.approx(0.010)


async def _play_game(service, opening, weak_is_white, weak_skill, strong_skill,
                     depth=4, max_plies=90):
    """Self-play one game; returns white's material balance at the end
    (mate counts as +/- a queen's worth beyond any material)."""
    board = Board(STARTPOS)
    moves = list(opening)
    for m in opening:
        board.push_uci(m)
    while board.outcome() == Board.ONGOING and len(moves) < max_plies:
        white_to_move = board.turn() == "w"
        skill = (
            weak_skill if white_to_move == weak_is_white else strong_skill
        )
        res = await service.search(
            STARTPOS, moves, depth=depth, skill_level=skill
        )
        assert res.best_move is not None
        moves.append(res.best_move)
        board.push_uci(res.best_move)
    material = _material_white_cp(board.fen())
    if board.outcome() == Board.CHECKMATE:
        material += -900 if board.turn() == "w" else 900
    return material


async def test_skill_weakening_decides_selfplay(service):
    """A level-1 (skill −9) engine must lose material en masse to a
    level-8 (skill 20) one — the VERDICT r4 'decisive score split' bar,
    adjudicated by material (the material net can't always convert to
    mate at depth 4, but it reliably wins material off a blundering
    opponent)."""
    strong_edge_cp = 0
    games = 0
    for i, opening in enumerate(OPENINGS):
        weak_is_white = i % 2 == 0
        material_white = await _play_game(
            service, opening, weak_is_white, weak_skill=-9, strong_skill=20
        )
        strong_edge_cp += -material_white if weak_is_white else material_white
        games += 1
    # Decisive: the strong side ends up better by at least two pawns per
    # game on average (in practice it is far more).
    assert strong_edge_cp / games >= 200, (
        f"skill weakening not decisive: strong edge "
        f"{strong_edge_cp / games:.0f} cp/game over {games} games"
    )


async def test_skill_pick_stays_legal_and_differs(service):
    """The weakened pick must be a legal root move, and across a set of
    midgame positions skill −9 must deviate from the full-strength
    choice at least once (the sampling actually engages)."""
    from tests.test_search import _random_fens

    fens = _random_fens(12, seed=71)
    deviations = 0
    for fen in fens:
        legal = set(Board(fen).legal_moves())
        strong = await service.search(fen, [], depth=4, skill_level=20)
        weak = await service.search(fen, [], depth=4, skill_level=-9)
        assert weak.best_move in legal
        if weak.best_move != strong.best_move:
            deviations += 1
    assert deviations >= 1, "skill -9 never deviated from full strength"


async def test_analysis_unaffected_by_default_skill(service):
    """Default (analysis) searches take the full-strength path: the
    deepest rank-1 PV head IS the best move."""
    res = await service.search("6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [],
                               depth=4)
    assert res.best_move == "d1d8"


async def test_clock_bounds_think_time(service):
    """A play job whose clock allocation is far below the level movetime
    must come back in roughly the clock allocation, not the level's
    (stockfish.rs:316-336: the engine takes the tighter bound)."""
    work = Work(
        kind="move",
        id="clockjob1",
        level=SkillLevel.EIGHT,  # movetime 1000 ms, depth 22
        clock=Clock(wtime_centis=200, btime_centis=200, inc_seconds=0),
    )
    engine = TpuNnueEngine(service, EngineFlavor.OFFICIAL)
    pos = Position(
        work=work,
        position_id=0,
        flavor=EngineFlavor.OFFICIAL,
        variant=Variant.STANDARD,
        # A quiet midgame where depth 22 cannot finish instantly.
        root_fen="r1bqkb1r/pppp1ppp/2n2n2/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R w KQkq - 4 4",
        moves=[],
    )
    start = time.monotonic()
    response = await engine.go(pos)
    elapsed = time.monotonic() - start
    assert response.best_move is not None
    # Allocation = min(1000 ms, 2000/40 = 50 ms) → the stop fires ~50 ms
    # in; generous ceiling for slow CI, but far under the 1 s movetime.
    assert elapsed < 0.9, f"clock did not bound think time ({elapsed:.2f}s)"
