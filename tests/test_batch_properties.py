"""Property tests for batch assembly / skip handling / reassembly
(SURVEY.md §4: the reference has zero tests here; these pin the
invariants its runtime validation silently relies on)."""

import json

import pytest

# Property tests need the optional `hypothesis` package; skip the module
# (not a collection error) where it is not installed.
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from fishnet_tpu.chess.board import Board
from fishnet_tpu.ipc import Position, PositionResponse
from fishnet_tpu.protocol.types import AcquireResponseBody, Matrix, Score
from fishnet_tpu.protocol.types import STARTPOS
from fishnet_tpu.sched.queue import SKIP, AllSkipped, IncomingBatch, PendingBatch

ENDPOINT = "http://test/fishnet"


def random_game(seed: int, plies: int) -> list:
    """A random legal game line from the start position."""
    import numpy as np

    rng = np.random.default_rng(seed)
    board = Board(STARTPOS)
    moves = []
    for _ in range(plies):
        legal = board.legal_moves()
        if not legal or board.outcome() != Board.ONGOING:
            break
        mv = legal[int(rng.integers(len(legal)))]
        board.push_uci(mv)
        moves.append(mv)
    return moves


def acquired_body(moves, skips):
    data = {
        "work": {
            "type": "analysis",
            "id": "wkPROP01",
            "nodes": {"sf15": 1000, "sf14": 1000, "classical": 2000},
            "timeout": 7000,
        },
        "game_id": "propgame",
        "position": STARTPOS,
        "variant": "standard",
        "moves": " ".join(moves),
        "skipPositions": sorted(skips),
    }
    return AcquireResponseBody.from_json(json.loads(json.dumps(data)))


@settings(max_examples=40, deadline=None)
@given(
    seed=st.integers(0, 10_000),
    plies=st.integers(0, 24),
    skip_data=st.data(),
)
def test_expansion_counts_and_skips(seed, plies, skip_data):
    moves = random_game(seed, plies)
    n_positions = len(moves) + 1  # root + one per ply
    skips = skip_data.draw(
        st.sets(st.integers(0, n_positions - 1), max_size=n_positions)
    )

    try:
        batch = IncomingBatch.from_acquired(ENDPOINT, acquired_body(moves, skips))
    except AllSkipped:
        # Only legal when every position was skipped.
        assert len(skips) == n_positions
        return

    # Invariant 1: one slot per position, in ply order.
    assert len(batch.positions) == n_positions

    # Invariant 2: exactly the requested indices are SKIP...
    got_skips = {i for i, p in enumerate(batch.positions) if p is SKIP}
    assert got_skips == {s for s in skips if 0 <= s < n_positions}

    # Invariant 3: ...and every non-skip slot is a Position whose move
    # prefix replays the game up to its ply.
    for i, p in enumerate(batch.positions):
        if p is SKIP:
            continue
        assert isinstance(p, Position)
        assert p.position_id == i
        assert list(p.moves) == moves[:i]


@settings(max_examples=6, deadline=None)
@given(seed=st.integers(0, 2**16))
def test_fused_psqt_parity_property(seed):
    """Property pin of the ABI 9 device-PSQT contract: on RANDOM batch
    compositions (plain fulls, anchor seeds, persistent anchor deltas
    with swap, in-batch deltas, removal encodings), the fused kernel's
    PSQT accumulator (interpreter mode) is bit-identical to the XLA
    path and to an independent numpy chain walk — the same three-way
    agreement the deterministic test pins, over the composition space."""
    import numpy as np

    jnp = pytest.importorskip("jax.numpy")
    from test_ops import build_psqt_parity_batch, np_resolve_psqt

    from fishnet_tpu.ops.ft_gather import ft_accumulate

    n_features, l1, active = 64, 1024, 32
    rng = np.random.default_rng(seed)
    ft_w = np.vstack(
        [rng.integers(-50, 50, (n_features, l1)), np.zeros((1, l1))]
    ).astype(np.int16)
    ft_b = rng.integers(-20, 20, (l1,)).astype(np.int16)
    psqt_rows = np.vstack(
        [rng.integers(-3000, 3000, (n_features, 8)), np.zeros((1, 8))]
    ).astype(np.int32)
    idx, parent, delta_base = build_psqt_parity_batch(
        n_features, active, rng, n_blocks=3, block=3, n_tab=4
    )
    tab = rng.integers(-5000, 5000, (4, 2, l1)).astype(np.int32)
    ptab = rng.integers(-4000, 4000, (4, 2, 8)).astype(np.int32)
    args = dict(delta_base=delta_base, parent=jnp.asarray(parent),
                anchor_tab=jnp.asarray(tab), ft_psqt=jnp.asarray(psqt_rows),
                psqt_tab=jnp.asarray(ptab))
    acc_x, psqt_x = ft_accumulate(
        jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
        use_pallas=False, **args,
    )
    acc_f, psqt_f = ft_accumulate(
        jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
        interpret=True, **args,
    )
    assert np.array_equal(np.asarray(acc_x), np.asarray(acc_f))
    assert np.array_equal(np.asarray(psqt_x), np.asarray(psqt_f))
    ref = np_resolve_psqt(idx, parent, psqt_rows, ptab, delta_base)
    assert np.array_equal(np.asarray(psqt_x).astype(np.int64), ref)


@settings(max_examples=20, deadline=None)
@given(seed=st.integers(0, 10_000), plies=st.integers(1, 16))
def test_reassembly_order_independent(seed, plies):
    """Responses arriving in any order reassemble positionally."""
    import numpy as np

    moves = random_game(seed, plies)
    batch = IncomingBatch.from_acquired(ENDPOINT, acquired_body(moves, set()))
    pending = PendingBatch(
        work=batch.work, flavor=batch.flavor, variant=batch.variant,
        positions=[None] * len(batch.positions), started_at=0.0, url=batch.url,
    )

    rng = np.random.default_rng(seed)
    order = rng.permutation(len(batch.positions))
    for i in order:
        pos = batch.positions[i]
        scores = Matrix()
        pvs = Matrix()
        scores.set(1, 1, Score.cp(100 + int(pos.position_id)))
        pvs.set(1, 1, ["e2e4"] if pos.root_fen else [])
        assert pending.try_into_completed() is None
        pending.positions[pos.position_id] = PositionResponse(
            work=pos.work, position_id=pos.position_id, scores=scores,
            pvs=pvs, best_move=None, depth=1, nodes=7, time_seconds=0.01,
            nps=700, url=pos.url,
        )
    completed = pending.try_into_completed()
    assert completed is not None
    parts = completed.into_analysis()
    assert len(parts) == len(batch.positions)
    # Score i encodes position id i: reassembly preserved ply order.
    for i, part in enumerate(parts):
        assert part["score"]["cp"] == 100 + i
