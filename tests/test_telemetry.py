"""Telemetry subsystem tests (doc/observability.md).

Covers the registry primitives (per-thread cells, collector lifecycle),
Prometheus text-format rendering, the exposition server (the tier-1
`make metrics-smoke` contract scrape), the span flight recorder +
SIGUSR2 dump, the net/api outcome counters against the fake server, the
debounced stats file, and agreement between `/metrics` and
`SearchService.counters()` / `StatsRecorder` totals under real load.
"""

import asyncio
import json
import os
import re
import signal
import threading
import urllib.request

import pytest

from fishnet_tpu import telemetry
from fishnet_tpu.net import api as api_mod
from fishnet_tpu.telemetry.exporter import MetricsExporter
from fishnet_tpu.telemetry.registry import MetricsRegistry
from fishnet_tpu.telemetry.spans import (
    RECORDER,
    STAGES,
    SpanRecorder,
    install_signal_dump,
)
from fishnet_tpu.utils.logger import Logger
from fishnet_tpu.utils.stats import StatsRecorder, register_stats_collector
from tests.fake_server import VALID_KEY, FakeServer


@pytest.fixture
def tel_enabled():
    telemetry.enable()
    try:
        yield
    finally:
        telemetry.disable()


# -- Prometheus text-format validation --------------------------------------

_HELP_RE = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram|summary|untyped)$"
)
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\})?'
    r" -?[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?$"
)


def assert_prometheus_format(text: str) -> dict:
    """Validate exposition-format 0.0.4 syntax; return {family: type}."""
    types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE"):
            m = _TYPE_RE.match(line)
            assert m, f"bad TYPE line: {line!r}"
            types[m.group(1)] = m.group(2)
        elif line.startswith("#"):
            assert _HELP_RE.match(line), f"bad comment line: {line!r}"
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"bad sample line: {line!r}"
            name = m.group(1)
            family = re.sub(r"_(bucket|sum|count)$", "", name)
            assert name in types or family in types, f"untyped sample: {name}"
    return types


def _sample_value(text: str, name: str, **labels) -> float:
    """Parse one sample's value out of exposition text."""
    for line in text.splitlines():
        if not line.startswith(name + "{") and not line.startswith(name + " "):
            continue
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"sample {name}{labels} not found")


# -- registry primitives ----------------------------------------------------


def test_counter_aggregates_across_threads():
    reg = MetricsRegistry()
    c = reg.counter("t_counter_total", "test")
    threads = [
        threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8000


def test_counter_labels():
    reg = MetricsRegistry()
    c = reg.counter("t_labeled_total", "test", labelnames=("kind",))
    c.inc(kind="a")
    c.inc(2, kind="b")
    assert c.value(kind="a") == 1
    assert c.value(kind="b") == 2
    with pytest.raises(ValueError):
        c.inc(wrong="x")


def test_instrument_type_conflict_and_reuse():
    reg = MetricsRegistry()
    c = reg.counter("t_dup", "test")
    assert reg.counter("t_dup", "test") is c  # idempotent re-registration
    with pytest.raises(ValueError):
        reg.gauge("t_dup", "test")


def test_gauge_set_and_function():
    reg = MetricsRegistry()
    g = reg.gauge("t_gauge", "test")
    g.set(41.0)
    assert g.collect().samples[0].value == 41.0
    g.set_function(lambda: 7.0)
    assert g.collect().samples[0].value == 7.0


def test_histogram_buckets_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("t_hist", "test", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    fam = h.collect()
    by_le = {
        s.labels["le"]: s.value for s in fam.samples if s.name == "t_hist_bucket"
    }
    assert by_le == {"0.1": 1, "1": 3, "10": 4, "+Inf": 5}
    count = next(s for s in fam.samples if s.name == "t_hist_count")
    total = next(s for s in fam.samples if s.name == "t_hist_sum")
    assert count.value == 5
    assert total.value == pytest.approx(56.05)


def test_collector_lifecycle():
    reg = MetricsRegistry()
    calls = []

    def good():
        calls.append("good")
        return [telemetry.counter_family("t_coll_total", "test", 3)]

    state = {"alive": True}

    def dying():
        # Weakref-to-owner idiom: None once the owner is gone.
        return [] if state["alive"] else None

    def bad():
        raise RuntimeError("boom")

    reg.register_collector(good, name="good")
    reg.register_collector(dying, name="dying")
    reg.register_collector(bad, name="bad")

    fams = {f.name: f for f in reg.collect()}
    assert fams["t_coll_total"].samples[0].value == 3
    # The raising collector is counted, and the scrape survives it.
    errs = fams["fishnet_telemetry_collector_errors_total"]
    assert any(
        s.labels.get("collector") == "bad" and s.value == 1 for s in errs.samples
    )

    state["alive"] = False
    reg.collect()  # dying returns None -> self-unregisters
    with reg._lock:
        names = [name for name, _ in reg._collectors.values()]
    assert "dying" not in names and "good" in names


def test_unregister_collector_prevents_further_calls():
    reg = MetricsRegistry()
    calls = []
    token = reg.register_collector(lambda: calls.append(1) or [], name="x")
    reg.collect()
    reg.unregister_collector(token)
    reg.collect()
    assert calls == [1]


def test_render_prometheus_format():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "counter with\nnewline help", labelnames=("q",))
    c.inc(q='va"l\\ue')  # label escaping
    reg.gauge("t_g", "gauge").set(1.5)
    reg.histogram("t_h", "hist", buckets=(0.5,)).observe(0.1)
    types = assert_prometheus_format(reg.render_prometheus())
    assert types == {
        "fishnet_telemetry_collector_errors_total": "counter",
        "t_total": "counter",
        "t_g": "gauge",
        "t_h": "histogram",
    }


def test_render_json_snapshot():
    reg = MetricsRegistry()
    reg.counter("t_total", "test").inc(2)
    snap = reg.render_json()
    assert snap["metrics"]["t_total"]["type"] == "counter"
    assert snap["metrics"]["t_total"]["samples"][0]["value"] == 2


# -- exposition server: the tier-1 metrics-smoke contract scrape ------------

#: Families every process exports unconditionally (module-level
#: instruments in net/api.py + the registry's own error counter). The
#: names are the doc/observability.md contract.
CONTRACT_FAMILIES = (
    "fishnet_api_request_seconds",
    "fishnet_api_requests_total",
    "fishnet_api_rejected_total",
    "fishnet_api_suspensions_total",
    "fishnet_api_suspended_seconds_total",
    "fishnet_telemetry_collector_errors_total",
)


def _scrape(exporter: MetricsExporter, path: str) -> bytes:
    with urllib.request.urlopen(f"{exporter.url}{path}", timeout=10) as res:
        return res.read()


def test_metrics_smoke():
    """Start the exporter on an ephemeral port, scrape /metrics, and
    validate Prometheus syntax + presence of the contract metrics."""
    exporter = MetricsExporter(port=0)
    try:
        text = _scrape(exporter, "/metrics").decode()
        types = assert_prometheus_format(text)
        for family in CONTRACT_FAMILIES:
            assert family in types, f"contract family missing: {family}"
        assert types["fishnet_api_request_seconds"] == "histogram"
        assert types["fishnet_api_requests_total"] == "counter"

        snap = json.loads(_scrape(exporter, "/json"))
        for family in CONTRACT_FAMILIES:
            assert family in snap["metrics"]
        assert _scrape(exporter, "/healthz") == b"ok\n"
        assert "spans" in json.loads(_scrape(exporter, "/spans"))
        with pytest.raises(urllib.request.HTTPError):
            _scrape(exporter, "/nope")
    finally:
        exporter.close()


def test_start_exporter_enables_telemetry(tmp_path, monkeypatch):
    monkeypatch.setenv("FISHNET_SPANS_FILE", str(tmp_path / "s.jsonl"))
    exporter = telemetry.start_exporter(0)
    try:
        assert telemetry.enabled()
        assert_prometheus_format(_scrape(exporter, "/metrics").decode())
    finally:
        exporter.close()
        telemetry.disable()


# -- span flight recorder ---------------------------------------------------


def test_ring_wraps_keeps_latest():
    rec = SpanRecorder(capacity=4)
    import time as _time

    for i in range(10):
        rec.record("pack", _time.monotonic(), i=i)
    got = [s["i"] for s in rec.spans()]
    assert got == [6, 7, 8, 9]


def test_dump_jsonl_format(tmp_path):
    rec = SpanRecorder()
    import time as _time

    for stage in STAGES:
        rec.record(stage, _time.monotonic(), n=1)
    path = tmp_path / "spans.jsonl"
    rec.dump(str(path), reason="test")
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    header, spans = lines[0], lines[1:]
    assert header["format"] == "fishnet-spans/2"
    assert header["reason"] == "test"
    assert header["spans"] == len(spans) == len(STAGES)
    assert {s["stage"] for s in spans} == set(STAGES)
    for s in spans:
        assert s["dur_ms"] >= 0 and "thread" in s


def test_sigusr2_dumps_flight_recorder(tmp_path, monkeypatch):
    if not hasattr(signal, "SIGUSR2"):
        pytest.skip("no SIGUSR2 on this platform")
    path = tmp_path / "sig-spans.jsonl"
    monkeypatch.setenv("FISHNET_SPANS_FILE", str(path))
    import time as _time

    for stage in STAGES:
        RECORDER.record(stage, _time.monotonic())
    assert install_signal_dump()
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = _time.monotonic() + 5
    while not path.exists() and _time.monotonic() < deadline:
        _time.sleep(0.01)
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert lines[0]["reason"] == "SIGUSR2"
    assert {s["stage"] for s in lines[1:]} >= set(STAGES)


# -- net/api outcome counters (429 suspension + reject paths) ---------------

pytestmark = pytest.mark.anyio


def _acquire_hist():
    return api_mod._REQUEST_SECONDS.labels(endpoint="acquire").snapshot()


async def test_api_reject_counters():
    """400/401/403/406 on acquire: rejected counter + ok outcome +
    a latency observation land in the instruments."""
    async with FakeServer() as server:
        server.lichess.reject_with = 406
        stub, actor = api_mod.channel(
            server.endpoint, VALID_KEY, Logger(verbose=0)
        )
        task = asyncio.create_task(actor.run())
        rej0 = api_mod._REJECTS.value(endpoint="acquire", status="406")
        ok0 = api_mod._REQUESTS.value(endpoint="acquire", outcome="ok")
        counts0, sum0, n0 = _acquire_hist()
        try:
            acquired = await stub.acquire(slow=False)
        finally:
            actor.stop()
            await asyncio.wait_for(task, timeout=10)
        assert acquired is not None and acquired.kind.value == "rejected"
        assert api_mod._REJECTS.value(endpoint="acquire", status="406") == rej0 + 1
        # A reject is a *successful* round trip (outcome=ok): the server
        # answered; it is the answer that stops the queue.
        assert api_mod._REQUESTS.value(endpoint="acquire", outcome="ok") == ok0 + 1
        counts1, sum1, n1 = _acquire_hist()
        assert n1 == n0 + 1 and sum1 >= sum0
        # Cumulative-bucket sanity: every bucket is monotone in time and
        # the overflow (+Inf) count equals the total observation count.
        assert all(c1 >= c0 for c0, c1 in zip(counts0, counts1))
        assert sum(counts1) <= n1


async def test_api_rate_limited_counters():
    """429 on acquire: rate_limited outcome + suspension counters, and
    the suspension-seconds counter accrues the >= 60 s backoff."""
    async with FakeServer() as server:
        server.lichess.reject_with = 429
        stub, actor = api_mod.channel(
            server.endpoint, VALID_KEY, Logger(verbose=0)
        )
        task = asyncio.create_task(actor.run())
        rl0 = api_mod._REQUESTS.value(endpoint="acquire", outcome="rate_limited")
        susp0 = api_mod._SUSPENSIONS.value()
        sec0 = api_mod._SUSPENDED_SECONDS.value()
        _, _, n0 = _acquire_hist()
        try:
            # The future is failed before the actor parks in its 60 s
            # suspension sleep, so this returns promptly (None).
            acquired = await asyncio.wait_for(stub.acquire(slow=False), timeout=10)
        finally:
            task.cancel()
            with pytest.raises(asyncio.CancelledError):
                await task
        assert acquired is None
        assert (
            api_mod._REQUESTS.value(endpoint="acquire", outcome="rate_limited")
            == rl0 + 1
        )
        assert api_mod._SUSPENSIONS.value() == susp0 + 1
        assert api_mod._SUSPENDED_SECONDS.value() >= sec0 + 60.0
        _, _, n1 = _acquire_hist()
        assert n1 == n0 + 1


# -- stats recorder: debounce + collector -----------------------------------


def test_default_stats_file_no_home(monkeypatch):
    from pathlib import Path

    from fishnet_tpu.utils import stats as stats_mod

    def no_home():
        raise RuntimeError("no home directory")

    monkeypatch.setattr(Path, "home", no_home)
    assert stats_mod.default_stats_file() is None


def test_stats_flush_debounced(tmp_path):
    path = tmp_path / "stats.json"
    rec = StatsRecorder(cores=2, stats_file=path, flush_interval=3600.0)
    rec.record_batch(positions=10, nodes=1000, nnue_nps=5000)
    # First batch flushes immediately so short runs persist.
    assert json.loads(path.read_text())["total_batches"] == 1
    rec.record_batch(positions=10, nodes=1000)
    rec.record_batch(positions=10, nodes=1000)
    # Within the interval: on-disk copy is stale by design.
    assert json.loads(path.read_text())["total_batches"] == 1
    rec.flush()
    assert json.loads(path.read_text())["total_batches"] == 3
    mtime = path.stat().st_mtime_ns
    rec.flush()  # not dirty -> no rewrite
    assert path.stat().st_mtime_ns == mtime


def test_stats_collector_exposes_totals():
    rec = StatsRecorder(cores=4, no_stats_file=True)
    rec.record_batch(positions=7, nodes=420, nnue_nps=1000)
    token = register_stats_collector(rec)
    try:
        text = telemetry.REGISTRY.render_prometheus()
        assert _sample_value(text, "fishnet_stats_batches_total") == 1
        assert _sample_value(text, "fishnet_stats_positions_total") == 7
        assert _sample_value(text, "fishnet_stats_nodes_total") == 420
        assert _sample_value(text, "fishnet_nnue_nps") > 0
    finally:
        telemetry.REGISTRY.unregister_collector(token)


# -- SearchService under load: /metrics agrees with counters() --------------


async def test_service_metrics_agree_with_counters(tmp_path, monkeypatch, tel_enabled):
    """Acceptance: scrape a live service and require exact agreement
    with counters(), plus pipeline-stage spans from the driver."""
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.search.service import SearchService

    monkeypatch.setenv("FISHNET_SPANS_FILE", str(tmp_path / "svc.jsonl"))
    svc = SearchService(
        weights=NnueWeights.random(seed=5),
        pool_slots=32,
        batch_capacity=32,
        tt_bytes=1 << 20,
        backend="scalar",
    )
    exporter = MetricsExporter(port=0)
    try:
        await asyncio.gather(*(
            svc.search(
                "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
                [],
                depth=3,
            )
            for _ in range(4)
        ))
        # Quiesced drivers: two successive counter reads must agree, and
        # the scrape between them must match exactly.
        for _ in range(50):
            before = svc.counters()
            text = _scrape(exporter, "/metrics").decode()
            if svc.counters() == before:
                break
            await asyncio.sleep(0.05)
        else:
            pytest.fail("service never quiesced")
        assert_prometheus_format(text)
        assert _sample_value(text, "fishnet_pool_nodes_total") == before["nodes"]
        assert _sample_value(text, "fishnet_pool_steps_total") == before["steps"]
        assert (
            _sample_value(text, "fishnet_pool_evals_shipped_total")
            == before["evals_shipped"]
        )
        assert (
            _sample_value(text, "fishnet_service_eval_steps_total")
            == before["eval_steps"]
        )
        assert (
            _sample_value(text, "fishnet_service_wire_bytes_total")
            == before["wire_bytes"]
        )
        assert _sample_value(text, "fishnet_service_info", backend="scalar") == 1
        # The driver recorded spans for the service-side pipeline stages.
        assert RECORDER.stages_seen() >= {
            "pack", "device_step", "wire_decode", "postprocess",
        }
    finally:
        exporter.close()
        svc.close()
    # close() unregisters the collector: the next scrape must not see
    # the service families (the freed-pool guard).
    text = telemetry.REGISTRY.render_prometheus()
    assert "fishnet_pool_nodes_total" not in text


# -- full pipeline: all six stages in one SIGUSR2 dump ----------------------


async def test_pipeline_spans_cover_all_stages(tmp_path, monkeypatch, tel_enabled):
    """Fake server -> client -> queue -> TPU engine -> service, with
    telemetry on: the SIGUSR2 dump covers all six pipeline stages."""
    if not hasattr(signal, "SIGUSR2"):
        pytest.skip("no SIGUSR2 on this platform")
    from fishnet_tpu.client import Client
    from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.search.service import SearchService

    path = tmp_path / "pipeline.jsonl"
    monkeypatch.setenv("FISHNET_SPANS_FILE", str(path))
    svc = SearchService(
        weights=NnueWeights.random(seed=11),
        pool_slots=32,
        batch_capacity=32,
        tt_bytes=1 << 20,
        backend="scalar",
    )
    try:
        async with FakeServer() as server:
            work_id = server.lichess.add_analysis_job(
                moves="e2e4 c7c5", nodes=200
            )
            client = Client(
                endpoint=server.endpoint,
                key=VALID_KEY,
                cores=2,
                engine_factory=TpuNnueEngineFactory(svc),
                logger=Logger(verbose=0),
                max_backoff=0.2,
            )
            await client.start()
            deadline = asyncio.get_running_loop().time() + 60
            while (
                work_id not in server.lichess.analyses
                and asyncio.get_running_loop().time() < deadline
            ):
                await asyncio.sleep(0.05)
            await client.stop()
            assert work_id in server.lichess.analyses
    finally:
        svc.close()
    assert install_signal_dump()
    os.kill(os.getpid(), signal.SIGUSR2)
    import time as _time

    deadline = _time.monotonic() + 5
    while _time.monotonic() < deadline:
        if path.exists() and any(
            json.loads(l).get("reason") == "SIGUSR2"
            for l in path.read_text().splitlines()
            if '"format"' in l
        ):
            break
        _time.sleep(0.01)
    stages = {
        json.loads(l)["stage"]
        for l in path.read_text().splitlines()
        if '"stage"' in l
    }
    assert stages >= set(STAGES), f"missing stages: {set(STAGES) - stages}"
