"""Tests for the UCI subprocess engine driver against a scripted fake
engine (tests/fake_uci_engine.py) — the driver-level analogue of the
reference's manual Stockfish testing (SURVEY.md §4)."""

import os
import sys

import pytest

from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.uci import UciEngine, UciEngineFactory, _parse_info_line
from fishnet_tpu.ipc import Position
from fishnet_tpu.protocol.types import (
    Clock,
    EngineFlavor,
    NodeLimit,
    Score,
    SkillLevel,
    Variant,
    Work,
)

from fishnet_tpu.protocol.types import STARTPOS

pytestmark = pytest.mark.anyio

FAKE = os.path.join(os.path.dirname(__file__), "fake_uci_engine.py")


def fake_engine(flavor=EngineFlavor.OFFICIAL):
    return UciEngine(sys.executable, flavor, args=[FAKE])


def analysis_work(multipv=None, depth=None):
    return Work(
        kind="analysis",
        id="testbatch01",
        nodes=NodeLimit(classical=4_050_000, sf15=1_500_000),
        depth=depth,
        multipv=multipv,
        timeout_ms=7000,
    )


def analysis_position(work=None, moves=()):
    return Position(
        work=work or analysis_work(),
        position_id=0,
        flavor=EngineFlavor.OFFICIAL,
        variant=Variant.STANDARD,
        root_fen=STARTPOS,
        moves=list(moves),
    )


async def test_analysis_search(monkeypatch):
    monkeypatch.delenv("FAKE_UCI_DIE_ON_GO", raising=False)
    engine = fake_engine()
    try:
        response = await engine.go(analysis_position(moves=["e2e4", "e7e5"]))
    finally:
        await engine.close()
    assert response.best_move == "e2e4"
    assert response.depth == 3
    # The final (upperbound) info line still updates node/time counters,
    # even though its score is not recorded.
    assert response.nodes == 4000
    assert response.nps == 500000
    assert response.scores.best() == Score.cp(30)
    assert response.pvs.best() == ["e2e4", "e7e5"]
    # The depth-4 upperbound line must not be recorded.
    assert response.scores.best() != Score.cp(99)


async def test_multipv_matrix():
    work = analysis_work(multipv=3)
    engine = fake_engine()
    try:
        response = await engine.go(analysis_position(work=work))
    finally:
        await engine.close()
    rows = response.scores.to_json()
    assert len(rows) == 3  # one row per pv
    assert rows[0][3] == Score.cp(30)
    assert rows[2][3] == Score.cp(20)


async def test_move_job():
    work = Work(
        kind="move",
        id="testmove01",
        level=SkillLevel.EIGHT,
        clock=Clock(wtime_centis=3000, btime_centis=3000, inc_seconds=2),
    )
    engine = fake_engine(flavor=EngineFlavor.MULTI_VARIANT)
    try:
        response = await engine.go(
            Position(
                work=work,
                position_id=0,
                flavor=EngineFlavor.MULTI_VARIANT,
                variant=Variant.STANDARD,
                root_fen=STARTPOS,
                moves=[],
            )
        )
    finally:
        await engine.close()
    assert response.best_move == "e2e4"


async def test_engine_crash_raises(monkeypatch):
    monkeypatch.setenv("FAKE_UCI_DIE_ON_GO", "1")
    engine = fake_engine()
    try:
        with pytest.raises(EngineError):
            await engine.go(analysis_position())
    finally:
        monkeypatch.delenv("FAKE_UCI_DIE_ON_GO")
        await engine.close()


async def test_bestmove_without_score_raises(monkeypatch):
    monkeypatch.setenv("FAKE_UCI_NO_SCORE", "1")
    engine = fake_engine()
    try:
        with pytest.raises(EngineError):
            await engine.go(analysis_position())
    finally:
        monkeypatch.delenv("FAKE_UCI_NO_SCORE")
        await engine.close()


async def test_terminal_position_mate_score(monkeypatch):
    """Checkmate/stalemate: `score mate 0` arrives with no pv and
    `bestmove (none)` — must produce a response, not an engine error."""
    monkeypatch.setenv("FAKE_UCI_MATE", "1")
    engine = fake_engine()
    try:
        response = await engine.go(analysis_position())
    finally:
        monkeypatch.delenv("FAKE_UCI_MATE")
        await engine.close()
    assert response.best_move is None
    assert response.scores.best() == Score.mate(0)
    assert response.pvs.best() == []


async def test_missing_binary_raises():
    engine = UciEngine("/nonexistent/engine-binary", EngineFlavor.OFFICIAL)
    with pytest.raises(EngineError):
        await engine.go(analysis_position())
    await engine.close()


async def test_factory_routes_flavors():
    factory = UciEngineFactory(sys.executable, args=[FAKE])
    official = await factory.create(EngineFlavor.OFFICIAL)
    variant = await factory.create(EngineFlavor.MULTI_VARIANT)
    assert isinstance(official, UciEngine)
    assert official.flavor is EngineFlavor.OFFICIAL
    assert variant.flavor is EngineFlavor.MULTI_VARIANT
    await official.close()
    await variant.close()


async def test_uci_end_to_end_with_client():
    """The minimum end-to-end slice of SURVEY.md §7 step 3: a real
    analysis batch from the fake lichess server through a (scripted) UCI
    engine subprocess and back."""
    import asyncio

    from fishnet_tpu.client import Client
    from fishnet_tpu.utils.logger import Logger
    from tests.fake_server import VALID_KEY, FakeServer

    async def wait_for(predicate, timeout=10.0):
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            if predicate():
                return True
            await asyncio.sleep(0.02)
        return False

    async with FakeServer() as server:
        work_id = server.lichess.add_analysis_job(moves="e2e4 e7e5 g1f3")
        client = Client(
            endpoint=server.endpoint,
            key=VALID_KEY,
            cores=2,
            engine_factory=UciEngineFactory(sys.executable, args=[FAKE]),
            logger=Logger(verbose=0),
            max_backoff=0.2,
        )
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()

        parts = server.lichess.analyses[work_id]["analysis"]
        assert len(parts) == 4
        for part in parts:
            assert part["depth"] == 3
            assert part["score"] == {"cp": 30}
            assert part["pv"] == "e2e4 e7e5"


def test_parse_info_line():
    fields = _parse_info_line(
        "info depth 20 seldepth 30 multipv 2 score mate -3 nodes 12345 nps 1000 time 44 pv a2a4 b7b5".split()
    )
    assert fields["depth"] == 20
    assert fields["multipv"] == 2
    assert fields["score"] == Score.mate(-3)
    assert fields["pv"] == ["a2a4", "b7b5"]
    assert fields["nodes"] == 12345
    # `string` payloads terminate parsing
    assert "pv" not in _parse_info_line("info string hello pv world".split())
