"""The full slice: fake lichess server -> client -> queue -> workers ->
TpuNnueEngine -> batched fiber searches -> JAX NNUE eval -> submitted
analysis. This is the reference's whole pipeline with the engine tier
replaced by the batched TPU backend."""

import asyncio

import pytest

from fishnet_tpu.client import Client
from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import SearchService
from fishnet_tpu.utils.logger import Logger
from tests.fake_server import VALID_KEY, FakeServer

pytestmark = pytest.mark.anyio


@pytest.fixture(scope="module")
def service():
    svc = SearchService(
        weights=NnueWeights.random(seed=11),
        pool_slots=64,
        batch_capacity=64,
        tt_bytes=16 << 20,
        backend="jax",
    )
    yield svc
    svc.close()


async def wait_for(predicate, timeout=60.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(0.05)
    return False


async def test_analysis_with_real_engine(service):
    async with FakeServer() as server:
        moves = "e2e4 c7c5 g1f3 d7d6 d2d4 c5d4"
        work_id = server.lichess.add_analysis_job(
            moves=moves, skip_positions=[2], nodes=400
        )
        client = Client(
            endpoint=server.endpoint,
            key=VALID_KEY,
            cores=4,
            engine_factory=TpuNnueEngineFactory(service),
            logger=Logger(),
            max_backoff=0.2,
        )
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()

        parts = server.lichess.analyses[work_id]["analysis"]
        assert len(parts) == 7
        assert parts[2] == {"skipped": True}
        for i, part in enumerate(parts):
            if i == 2:
                continue
            assert "score" in part and ("cp" in part["score"] or "mate" in part["score"])
            assert part["depth"] >= 1
            assert part["nodes"] >= 1
            # Real engine: PV must be present and start with a legal move
            # (4 chars minimum).
            assert len(part.get("pv", "x" * 4)) >= 4


async def test_move_job_with_real_engine(service):
    async with FakeServer() as server:
        work_id = server.lichess.add_move_job(moves="e2e4", level=3)
        client = Client(
            endpoint=server.endpoint,
            key=VALID_KEY,
            cores=2,
            engine_factory=TpuNnueEngineFactory(service),
            logger=Logger(),
            max_backoff=0.2,
        )
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.moves)
        await client.stop()
        best = server.lichess.moves[work_id]["move"]["bestmove"]
        assert best is not None and len(best) >= 4


async def test_mate_position_reported(service):
    async with FakeServer() as server:
        # Game ending in fool's mate: final ply is checkmate.
        moves = "f2f3 e7e5 g2g4 d8h4"
        work_id = server.lichess.add_analysis_job(moves=moves, nodes=300)
        client = Client(
            endpoint=server.endpoint,
            key=VALID_KEY,
            cores=2,
            engine_factory=TpuNnueEngineFactory(service),
            logger=Logger(),
            max_backoff=0.2,
        )
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()
        parts = server.lichess.analyses[work_id]["analysis"]
        # Final position: white is checkmated -> depth 0, mate 0, no pv.
        final = parts[-1]
        assert final["score"] == {"mate": 0}
        assert final["depth"] == 0
        assert "pv" not in final
        # The ply before must see mate in 1.
        assert parts[-2]["score"] == {"mate": 1}
