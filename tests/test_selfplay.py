"""Self-play generation and the closed AZ training loop."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fishnet_tpu.models.az import AzConfig, init_az_params
from fishnet_tpu.models.az_encoding import INPUT_PLANES, POLICY_SIZE
from fishnet_tpu.search.mcts import MctsConfig, MctsPool
from fishnet_tpu.train import AzTrainer
from fishnet_tpu.train.selfplay import SelfPlayConfig, play_games, selfplay_batch

TINY = AzConfig(channels=16, blocks=2, value_hidden=16)


@pytest.fixture(scope="module")
def pool():
    params = init_az_params(jax.random.PRNGKey(7), TINY)
    return MctsPool(params, MctsConfig(batch_capacity=128, az=TINY))


def test_selfplay_games_complete(pool):
    games = play_games(
        pool, SelfPlayConfig(games=4, visits=12, max_plies=24), seed=0
    )
    assert len(games) == 4
    for g in games:
        assert g.outcome_white in (-1.0, 0.0, 1.0)
        assert 1 <= len(g.records) <= 24
        assert len(g.moves) == len(g.records)


def test_selfplay_batch_shapes_and_targets(pool):
    batch = selfplay_batch(
        pool, SelfPlayConfig(games=3, visits=12, max_plies=16), seed=1
    )
    n = batch["planes"].shape[0]
    assert batch["planes"].shape == (n, 8, 8, INPUT_PLANES)
    assert batch["policy_target"].shape == (n, POLICY_SIZE)
    assert batch["value_target"].shape == (n,)
    sums = batch["policy_target"].sum(axis=1)
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)
    assert set(np.unique(batch["value_target"])) <= {-1.0, 0.0, 1.0}


def test_closed_training_loop(pool):
    # generate -> train: one generation of self-play feeds AzTrainer and
    # the loss decreases when overfitting that generation.
    batch_np = selfplay_batch(
        pool, SelfPlayConfig(games=3, visits=12, max_plies=12), seed=2
    )
    batch = {k: jnp.asarray(v) for k, v in batch_np.items()}
    trainer = AzTrainer(cfg=TINY, learning_rate=3e-3)
    state = trainer.init(seed=0)
    losses = []
    for _ in range(15):
        state, metrics = trainer.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
