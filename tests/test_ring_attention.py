"""Ring attention vs single-device reference on the virtual 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fishnet_tpu.ops.ring_attention import reference_attention, ring_attention


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(np.array(devices[:8]), ("sp",))


def _qkv(seed, b=2, s=64, h=4, d=16):
    rng = np.random.default_rng(seed)
    shape = (b, s, h, d)
    return (
        jnp.asarray(rng.normal(0, 1, shape).astype(np.float32)),
        jnp.asarray(rng.normal(0, 1, shape).astype(np.float32)),
        jnp.asarray(rng.normal(0, 1, shape).astype(np.float32)),
    )


def test_ring_matches_reference(mesh):
    q, k, v = _qkv(0)
    ref = reference_attention(q, k, v)
    out = ring_attention(q, k, v, mesh, "sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_causal_matches_reference(mesh):
    q, k, v = _qkv(1)
    ref = reference_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_jits_and_shards(mesh):
    q, k, v = _qkv(2, s=128)
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, "sp", causal=True))
    out = fn(q, k, v)
    assert out.shape == q.shape
    assert np.isfinite(np.asarray(out)).all()
