"""Chess rules tests against the native core: perft vectors, FEN
round-trips, castling notations, en-passant legality normalization."""

import pytest

from fishnet_tpu.chess import (
    Board,
    IllegalMoveError,
    InvalidFenError,
    STARTPOS_FEN,
    UnsupportedVariantError,
)
from fishnet_tpu.protocol.types import Variant

KIWIPETE = "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1"


def test_startpos():
    b = Board()
    assert b.fen() == STARTPOS_FEN
    assert b.turn() == "w"
    assert len(b.legal_moves()) == 20
    assert not b.is_check()
    assert b.outcome() == Board.ONGOING


@pytest.mark.parametrize(
    "fen,depth,nodes",
    [
        (STARTPOS_FEN, 4, 197281),
        (KIWIPETE, 3, 97862),
        ("8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1", 5, 674624),
        ("r3k2r/Pppp1ppp/1b3nbN/nP6/BBP1P3/q4N2/Pp1P2PP/R2Q1RK1 w kq - 0 1", 4, 422333),
        ("rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8", 3, 62379),
        ("r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10", 3, 89890),
    ],
)
def test_perft(fen, depth, nodes):
    assert Board(fen).perft(depth) == nodes


def test_play_game_and_replay():
    b = Board()
    for m in "e2e4 c7c5 c2c4 b8c6 g1e2 g8f6 b1c3 c6b4 g2g3 b4d3".split():
        b.push_uci(m)
    assert b.turn() == "w"
    assert b.fullmove_number() == 6


def test_illegal_move_rejected():
    b = Board()
    with pytest.raises(IllegalMoveError):
        b.push_uci("e2e5")
    with pytest.raises(IllegalMoveError):
        b.push_uci("e7e5")  # black's move, white to play
    with pytest.raises(IllegalMoveError):
        b.push_uci("junk")


def test_castling_both_notations():
    fen = "r3k2r/8/8/8/8/8/8/R3K2R w KQkq - 0 1"
    # Chess960-style: king takes own rook.
    b = Board(fen)
    b.push_uci("e1h1")
    assert "K" not in b.fen().split()[2]
    # Standard style also accepted on parse.
    b2 = Board(fen)
    b2.push_uci("e1g1")
    assert b.fen() == b2.fen()
    # Queenside.
    b3 = Board(fen)
    b3.push_uci("e1c1")
    b4 = Board(fen)
    b4.push_uci("e1a1")
    assert b3.fen() == b4.fen()


def test_castling_through_check_illegal():
    fen = "r3k2r/8/8/8/8/5r2/8/R3K2R w KQkq - 0 1"  # f3 rook covers f1
    b = Board(fen)
    moves = b.legal_moves()
    assert "e1h1" not in moves and "e1g1" not in moves
    assert "e1a1" in moves  # queenside still fine (b1/c1/d1 not covered)


def test_chess960_castling():
    # King b1, rook a1 and h1 (DFRC-style rights via file letters).
    fen = "1k5r/8/8/8/8/8/8/RK5R w HAh - 0 1"
    b = Board(fen)
    moves = b.legal_moves()
    assert "b1a1" in moves  # queenside: king onto rook square
    assert "b1h1" in moves


def test_chess960_rook_shelter_castle_illegal():
    # The castling rook on b1 shields the king's destination c1 from the
    # enemy rook on a1; once the rook moves to d1 the king would be in
    # check, so the castle must be illegal.
    b = Board("4k3/8/8/8/8/8/8/rR2K3 w B - 0 1")
    assert "e1b1" not in b.legal_moves()
    assert "e1c1" not in b.legal_moves()


def test_en_passant_only_when_legal():
    # After a double push creating a legal ep capture, the ep square shows.
    b = Board()
    b.push_uci("e2e4")
    b.push_uci("a7a6")
    b.push_uci("e4e5")
    b.push_uci("d7d5")
    assert " d6 " in b.fen()
    assert "e5d6" in b.legal_moves()
    # Double push with no adjacent enemy pawn: ep square normalized away.
    b2 = Board()
    b2.push_uci("e2e4")
    assert " - " in b2.fen()


def test_ep_pin_not_legal():
    # Capturing ep would expose the king to the rook: ep square omitted.
    fen = "8/8/8/KP5r/5p1k/8/4P3/8 b - - 0 1"
    b = Board(fen)
    b.push_uci("h4g5")  # reposition black king off the pin line first
    # now from white's perspective play e2e4 and check black can take ep
    b.push_uci("e2e4")
    assert "f4e3" in b.legal_moves()


def test_checkmate_and_stalemate():
    mate = Board("rnb1kbnr/pppp1ppp/8/4p3/6Pq/5P2/PPPPP2P/RNBQKBNR w KQkq - 1 3")
    assert mate.outcome() == Board.CHECKMATE
    assert mate.legal_moves() == []
    assert mate.is_check()
    stalemate = Board("7k/5Q2/6K1/8/8/8/8/8 b - - 0 1")
    assert stalemate.outcome() == Board.STALEMATE
    assert not stalemate.is_check()


def test_insufficient_material_draw():
    assert Board("8/8/4k3/8/8/3K4/8/8 w - - 0 1").outcome() == Board.DRAW
    assert Board("8/8/4k3/8/8/3KN3/8/8 w - - 0 1").outcome() == Board.DRAW
    assert Board("8/8/4k3/8/8/3K4/8/Q7 w - - 0 1").outcome() == Board.ONGOING


def test_promotion():
    b = Board("8/P6k/8/8/8/8/8/K7 w - - 0 1")
    b.push_uci("a7a8q")
    assert b.fen().startswith("Q7/7k")


def test_invalid_fen():
    with pytest.raises(InvalidFenError):
        Board("not a fen")
    with pytest.raises(InvalidFenError):
        Board("rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBN w KQkq - 0 1")


def test_all_variants_ungated():
    # Every lichess variant the reference serves via Fairy-Stockfish is
    # rules-complete in the native core (perft suite: tests/test_variants.py).
    standard = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    racing = "8/8/8/8/8/8/krbnNBRK/qrbnNBRQ w - - 0 1"
    for variant in Variant:
        fen = racing if variant is Variant.RACING_KINGS else standard
        assert Board(fen, variant).legal_moves()


def test_zobrist_transposition():
    a = Board()
    for m in "g1f3 g8f6 b1c3 b8c6".split():
        a.push_uci(m)
    b = Board()
    for m in "b1c3 b8c6 g1f3 g8f6".split():
        b.push_uci(m)
    assert a.zobrist_hash() == b.zobrist_hash()
    c = Board()
    assert c.zobrist_hash() != a.zobrist_hash()


def test_fen_roundtrip():
    for fen in [
        STARTPOS_FEN,
        KIWIPETE,
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    ]:
        assert Board(fen).fen() == fen
