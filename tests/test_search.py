"""Search-core tests through the SearchService: mates, draws, budgets,
MultiPV, and concurrent batched searches (JAX evaluator on CPU)."""

import asyncio

import pytest

from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import SearchService

pytestmark = pytest.mark.anyio

BACKENDS = ["scalar", "jax"]


@pytest.fixture(scope="module", params=BACKENDS)
def service(request):
    svc = SearchService(
        weights=NnueWeights.random(seed=3),
        pool_slots=64,
        batch_capacity=64,
        tt_bytes=16 << 20,
        backend=request.param,
    )
    yield svc
    svc.close()


async def test_mate_in_one(service):
    # Back-rank mate: Rd8#.
    res = await service.search("6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], depth=4)
    assert res.best_move == "d1d8"
    final = [l for l in res.lines if l.multipv == 1][-1]
    assert final.is_mate and final.value == 1


async def test_mated_root(service):
    # Fool's mate final position: white is checkmated.
    res = await service.search(
        "rnb1kbnr/pppp1ppp/8/4p3/6Pq/5P2/PPPPP2P/RNBQKBNR w KQkq - 1 3", [], depth=3
    )
    assert res.best_move is None
    assert res.lines[0].depth == 0
    assert res.lines[0].is_mate and res.lines[0].value == 0
    assert res.lines[0].pv == []


async def test_stalemate_root(service):
    res = await service.search("7k/5Q2/6K1/8/8/8/8/8 b - - 0 1", [], depth=3)
    assert res.best_move is None
    assert not res.lines[0].is_mate
    assert res.lines[0].value == 0


async def test_mate_in_two(service):
    # A classic: 1.Qf7+? no — use a known forced mate-in-2 position.
    # White: Kg1 Qg3 Rf1; Black: Kh8 pawn h7 g7. Qg3-b8? Use simpler:
    # ladder mate. White Ra1 Rb2 vs Kh8: Rb2-b8 is check... h7 escape.
    # Take a standard two-rook ladder: black king h8, rooks a7 b1.
    res = await service.search("7k/R7/8/8/8/8/8/1R4K1 w - - 0 1", [], depth=3)
    final = [l for l in res.lines if l.multipv == 1][-1]
    assert final.is_mate and final.value <= 2
    assert res.best_move == "b1b8"


async def test_node_budget_respected(service):
    res = await service.search(
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R w KQkq - 4 4",
        [], nodes=800,
    )
    # Depth-1 always completes; beyond that the budget binds (2x slack for
    # the final iteration's overshoot before the first allow_stop check).
    assert res.nodes <= 800 * 2
    assert res.depth >= 1
    assert res.best_move is not None


async def test_history_repetition_draw(service):
    # Same position reached before: searching it again on the same line
    # must allow the engine to know repetition = draw; here we just check
    # the search completes with history provided.
    moves = "g1f3 g8f6 f3g1 f6g8 g1f3 g8f6 f3g1 f6g8".split()
    res = await service.search(
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        moves, depth=2,
    )
    assert res.best_move is not None


async def test_multipv_ranks(service):
    res = await service.search(
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R w KQkq - 4 4",
        [], depth=3, multipv=3,
    )
    deepest = res.depth
    finals = {l.multipv: l for l in res.lines if l.depth == deepest}
    assert set(finals) == {1, 2, 3}
    first_moves = {finals[r].pv[0] for r in (1, 2, 3)}
    assert len(first_moves) == 3  # distinct root moves per rank


async def test_concurrent_searches_batch(service):
    fens = [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R w KQkq - 4 4",
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
        "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
    ] * 8
    results = await asyncio.gather(
        *[service.search(fen, [], nodes=500) for fen in fens]
    )
    assert len(results) == 32
    for res in results:
        assert res.best_move is not None
        assert res.nodes > 0


async def test_illegal_submit_rejected(service):
    with pytest.raises(Exception):
        await service.search("not a fen", [], depth=2)
    with pytest.raises(Exception):
        await service.search(
            "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
            ["e2e5"], depth=2,
        )


def test_netless_pool_refuses_standard_search():
    # A pool built without a scalar net (legal: variant/HCE-only use)
    # must refuse standard-variant submits instead of crashing in the
    # batched bridge's host-side PSQT walk (cpp fill_full needs the net).
    from fishnet_tpu.chess.board import _VARIANT_CODES
    from fishnet_tpu.chess.core import load
    from fishnet_tpu.protocol.types import Variant
    from fishnet_tpu.search.service import _bind_pool_api

    lib = load()
    _bind_pool_api(lib)
    pool = lib.fc_pool_new(4, 1 << 20, b"", 1)
    assert pool
    try:
        start = b"rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
        for use_scalar in (0, 1):
            rc = lib.fc_pool_submit(
                pool, -1, start, b"", 1000, 2, 1, 20, use_scalar,
                _VARIANT_CODES[Variant.STANDARD],
            )
            assert rc == -5
        # Variant searches evaluate with the HCE and stay serviceable.
        rc = lib.fc_pool_submit(
            pool, -1, start, b"", 1000, 1, 1, 20, 0,
            _VARIANT_CODES[Variant.ANTICHESS],
        )
        assert rc >= 0
    finally:
        lib.fc_pool_free(pool)


def test_pool_provide_guard_refuses_partial_with_anchors(tmp_path):
    """With persistent anchors enabled, fc_pool_provide must REFUSE a
    provide shorter than the step's batch (rc -1, nothing consumed) and
    leave the batch intact for a full retry: a partial provide would
    re-emit blocks whose entry-0 persistent delta references an
    anchor-table row the first emission already refreshed
    (cpp/src/pool.cpp fc_pool_provide, ABI 8 full-provide contract)."""
    import ctypes

    import numpy as np

    from fishnet_tpu.chess.board import _VARIANT_CODES
    from fishnet_tpu.chess.core import load
    from fishnet_tpu.protocol.types import Variant
    from fishnet_tpu.search.service import _bind_pool_api

    lib = load()
    _bind_pool_api(lib)
    net = str(tmp_path / "net.nnue")
    NnueWeights.random(seed=3).save(net)
    pool = lib.fc_pool_new(4, 1 << 20, net.encode(), 1)
    assert pool
    try:
        lib.fc_pool_set_anchors(pool, 1)
        rc = lib.fc_pool_submit(
            pool, -1,
            b"rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
            b"", 4000, 4, 1, 20, 0, _VARIANT_CODES[Variant.STANDARD],
        )
        assert rc >= 0
        cap = 256
        packed = np.empty((4 * cap + 4, 2, 8), np.uint16)
        offsets = np.empty(cap, np.int32)
        buckets = np.empty(cap, np.int32)
        slots = np.empty(cap, np.int32)
        parent = np.empty(cap, np.int32)
        rows = ctypes.c_int32(0)
        i32p = ctypes.POINTER(ctypes.c_int32)
        n = 0
        for _ in range(64):
            n = lib.fc_pool_step(
                pool, 0,
                packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                offsets.ctypes.data_as(i32p), buckets.ctypes.data_as(i32p),
                slots.ctypes.data_as(i32p), parent.ctypes.data_as(i32p),
                None, cap, 0, ctypes.byref(rows),
            )
            if n > 0:
                break
        assert n > 0, "NNUE search never suspended at a leaf"
        values = np.zeros(cap, np.int32)
        vp = values.ctypes.data_as(i32p)
        assert lib.fc_pool_provide(pool, 0, vp, n - 1) == -1  # refused
        assert lib.fc_pool_provide(pool, 0, vp, n) == n  # batch intact
    finally:
        lib.fc_pool_free(pool)


async def test_tiny_batch_capacity_clamped():
    """A capacity below the native core's largest eval block
    (EVAL_BLOCK_MAX=40, cpp/src/search.h:32) would livelock: emit_block is
    all-or-nothing, so the block could never ship. The service clamps."""
    from fishnet_tpu.search.service import MIN_BATCH_CAPACITY

    svc = SearchService(
        weights=NnueWeights.random(seed=5),
        pool_slots=8,
        batch_capacity=8,  # user asks for less than one block
        tt_bytes=1 << 20,
        backend="scalar",
    )
    try:
        assert svc.batch_capacity == MIN_BATCH_CAPACITY
        res = await svc.search(
            "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1", [], depth=3
        )
        assert res.best_move
    finally:
        svc.close()


async def test_eval_traffic_counters_and_adaptive_budget():
    """The pool's eval-traffic counters must account for every shipped
    slot (demand + speculative), and the speculation budget must shrink
    under batch-capacity pressure: many fibers sharing a small batch
    would otherwise starve each other with wasted prefetch slots."""
    svc = SearchService(
        weights=NnueWeights.random(seed=9),
        pool_slots=64,
        batch_capacity=40,  # MIN_BATCH_CAPACITY: heavy pressure
        tt_bytes=4 << 20,
        backend="jax",
    )
    try:
        tasks = [
            svc.search(
                "r1bqkbnr/pppp1ppp/2n5/4p3/4P3/5N2/PPPP1PPP/RNBQKB1R w KQkq - 2 3",
                [], nodes=600,
            )
            for _ in range(32)
        ]
        results = await asyncio.gather(*tasks)
        assert all(r.best_move for r in results)
        c = svc.counters()
        assert c["steps"] > 0
        assert c["suspensions"] > 0
        # Requests (demand + speculative) are served either by a shipped
        # batch slot; nothing is dropped.
        assert (
            c["demand_evals"] + c["prefetch_shipped"]
            == c["evals_shipped"]
        )
        assert c["evals_shipped"] <= c["step_capacity"]
        assert c["prefetch_hits"] <= c["prefetch_shipped"]
        # 32 fibers x blocks into a 40-slot batch overflows constantly;
        # the multiplicative-decrease path must have engaged.
        assert c["prefetch_budget"] < 40
    finally:
        svc.close()


def _see(fen, uci, variant=None):
    import ctypes

    from fishnet_tpu.chess import Board
    from fishnet_tpu.chess.core import load

    lib = load()
    if not hasattr(lib.fc_pos_see, "_bound"):
        lib.fc_pos_see.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.fc_pos_see.restype = ctypes.c_int
        lib.fc_pos_see._bound = True
    board = Board(fen) if variant is None else Board(fen, variant=variant)
    return lib.fc_pos_see(board._pos, uci.encode())


def test_see_exchange_oracle():
    """Static exchange evaluation against hand-computed capture
    sequences (cpp/src/search.cpp see()) — the capture-ordering and
    qsearch-pruning heuristic the reference gets from Stockfish's
    see_ge (VERDICT r2 missing feature #2)."""
    # Undefended pawn grab: clean +100.
    assert _see("1k6/8/8/2p5/8/8/2R5/1K6 w - - 0 1", "c2c5") == 100
    # Pawn takes pawn, defended by a pawn: equal trade.
    assert _see("1k6/8/3p4/2p5/3P4/8/8/1K6 w - - 0 1", "d4c5") == 0
    # Queen takes a pawn defended by a pawn: loses queen for two pawns.
    assert _see("1k6/8/3p4/2p5/8/8/2Q5/1K6 w - - 0 1", "c2c5") == 100 - 950
    # Doubled rooks vs pawn defended by pawn and rook (x-ray through the
    # front rook): RxP pxR stops there for white: -400.
    assert _see("4r1k1/8/3p4/4p3/8/8/4R3/4R1K1 w - - 0 1", "e2e5") == -400
    # En passant, retaken by a pawn: equal.
    assert _see("1k6/8/8/8/1pP5/8/1P6/1K6 b - c3 0 1", "b4c3") == 0
    # Quiet promotion into a rook's guard: new queen falls, pawn lost.
    assert _see("1r5k/P7/8/8/8/8/8/K7 w - - 0 1", "a7a8q") == -100
    # King recaptures a rook that grabbed a king-defended pawn.
    assert _see("8/8/8/3k4/3p4/8/3R4/3K4 w - - 0 1", "d2d4") == 100 - 500
    # Same, but the king's recapture square is covered by a bishop: the
    # king may not recapture into check, so the pawn grab stands.
    assert _see("8/8/8/3k4/3p4/8/1B1R4/3K4 w - - 0 1", "d2d4") == 100


def material_net():
    """A NnueWeights whose eval IS material: zero everywhere except the
    PSQT rows, which carry piece values (+ for the perspective's own
    pieces, - for the opponent's). material = (stm - opp)/2 then /16
    (spec FV_SCALE), so the probe margins clear by construction."""
    import numpy as np

    from fishnet_tpu.nnue import spec

    w = NnueWeights.random(seed=0)
    for f in ("ft_weight", "ft_bias", "l1_weight", "l1_bias", "l2_weight",
              "l2_bias", "out_weight", "out_bias"):
        getattr(w, f)[...] = 0
    vals = [3200, 10240, 10560, 16000, 30400, 0]  # P N B R Q K (x32)
    psqt = np.zeros((spec.NUM_FEATURES, spec.NUM_PSQT_BUCKETS), np.int32)
    for plane in range(spec.NUM_PLANES):
        pt, theirs = divmod(plane, 2) if plane < 10 else (5, 0)
        v = vals[pt] * (-1 if theirs else 1)
        for kb in range(spec.NUM_KING_BUCKETS):
            base = kb * spec.FEATURES_PER_BUCKET + plane * 64
            psqt[base : base + 64] = v
    w.ft_psqt[...] = psqt
    return w


def test_material_correlation_probe():
    """nnue_material_correlated (cpp/src/nnue.cpp) gates the SEE
    heuristics whose premise is a material-tracking eval: it must accept
    a material net and reject a random one (random nets drive the test
    and bench suites; pruning their searches by material logic was
    measured to inflate the tree ~35%)."""
    import ctypes
    import tempfile

    from fishnet_tpu.chess.core import load

    lib = load()
    if not hasattr(lib.fc_nnue_material_correlated, "_bound"):
        lib.fc_nnue_material_correlated.argtypes = [ctypes.c_void_p]
        lib.fc_nnue_material_correlated.restype = ctypes.c_int
        lib.fc_nnue_material_correlated._bound = True

    def probe(weights):
        with tempfile.NamedTemporaryFile(suffix=".nnue") as f:
            weights.save(f.name)
            err = ctypes.create_string_buffer(256)
            net = lib.fc_nnue_load(f.name.encode(), err, len(err))
            assert net, err.value
            try:
                return bool(lib.fc_nnue_material_correlated(net))
            finally:
                lib.fc_nnue_free(net)

    assert probe(material_net())
    assert not probe(NnueWeights.random(seed=7))  # the bench net
    assert not probe(NnueWeights.random(seed=21))  # the parity-suite net


def _random_fens(n, seed):
    import random

    from fishnet_tpu.chess import Board

    random.seed(seed)
    fens = []
    while len(fens) < n:
        b = Board()
        for _ in range(random.randrange(2, 60)):
            if b.outcome() != 0:
                break
            b.push_uci(random.choice(b.legal_moves()))
        if b.outcome() == 0:
            fens.append(b.fen())
    return fens


async def _parity_results(backend, weights, fens, depth=1,
                          tt_bytes=64 << 20, prefetch=None):
    # SEQUENTIAL submission, deliberately: the pool's TT is shared, so
    # concurrent searches interleave nondeterministically and bound/eval
    # entries from one search legitimately influence another — exact
    # cross-backend parity is only a sound invariant when both backends
    # process the same positions in the same order, one at a time (the
    # TT evolution is then a deterministic function of the sequence).
    # ``prefetch``: pin the speculation budget (adaptive off) so the
    # batched backend's TT insertions are a deterministic function of
    # the sequence too, not of batch-pressure history.
    svc = SearchService(
        weights=weights, pool_slots=16, batch_capacity=64,
        tt_bytes=tt_bytes, backend=backend,
    )
    if prefetch is not None:
        svc.set_prefetch(prefetch, adaptive=False)
    try:
        out = []
        for fen in fens:
            r = await svc.search(fen, [], depth=depth)
            line = [l for l in r.lines if l.multipv == 1][-1]
            out.append((line.value, line.is_mate, r.best_move))
        return out
    finally:
        svc.close()


_depth1_results = _parity_results


async def test_scalar_vs_jax_depth1_score_parity():
    """Depth-1 searches visit root (PV, no pruning) plus qsearch, where
    every pruning decision depends only on exact eval values — so the
    scalar backend and the batched JAX backend (whose blocks ship
    incremental delta entries through the sparse gather path) must agree
    on the score and best move exactly, position by position (VERDICT
    round 1: search-level parity at scale, not a handful of spot
    checks). Default-gate smoke: 40 positions; the bulk sweeps behind
    the `slow` marker are the at-scale venue (VERDICT r3 weak #4: the
    commit gate must stay fast on a 1-core box)."""
    fens = _random_fens(40, seed=99)
    weights = NnueWeights.random(seed=21)
    scalar = await _depth1_results("scalar", weights, fens)
    jax_out = await _depth1_results("jax", weights, fens)
    mismatches = [
        (fen, s, j) for fen, s, j in zip(fens, scalar, jax_out) if s != j
    ]
    assert not mismatches, (
        f"{len(mismatches)} of {len(fens)} positions diverged; first: "
        f"{mismatches[0]}"
    )


async def test_scalar_vs_jax_depth4_score_parity():
    """Parity where pruning actually fires: at depth >= 4 the search
    exercises TT bound cutoffs, null move, LMR re-searches, aspiration
    windows, and the (deterministic, HCE-margin) futility family — the
    scalar and batched backends must still agree exactly, proving the
    batched path's TT insertions (speculative prefetches, delta-entry
    evals) never perturb search *values* (VERDICT r2 weak #4: the
    margin-determinism machinery existed but was only proven at depth
    1, where pruning barely fires).

    The speculation budget is PINNED (adaptive off) so delta blocks
    still ship — the incremental path stays under test — while the
    batched backend's TT evolution is deterministic; the TT is sized so
    cluster-eviction differences (the one legitimate divergence channel:
    speculative entries exist only in the batched run and can tip a
    victim choice under pressure) stay out of reach.

    Default-gate smoke: 30 positions (the size VERDICT r3 weak #4
    prescribes for the commit gate); the full 150-position sweep is
    test_scalar_vs_jax_depth4_parity_full behind the `slow` marker."""
    await _depth4_parity_sweep(_random_fens(30, seed=77))


@pytest.mark.slow
async def test_scalar_vs_jax_depth4_parity_full():
    """The full 150-position depth-4 sweep (the pre-r4 default gate),
    now in the `slow` venue CI runs as its own job."""
    await _depth4_parity_sweep(_random_fens(150, seed=77))


async def _depth4_parity_sweep(fens):
    weights = NnueWeights.random(seed=21)
    kw = dict(depth=4, tt_bytes=256 << 20, prefetch=8)
    scalar = await _parity_results("scalar", weights, fens, **kw)
    jax_out = await _parity_results("jax", weights, fens, **kw)
    mismatches = [
        (fen, s, j) for fen, s, j in zip(fens, scalar, jax_out) if s != j
    ]
    assert not mismatches, (
        f"{len(mismatches)} of {len(fens)} positions diverged; first: "
        f"{mismatches[0]}"
    )


async def test_scalar_vs_jax_depth4_variants_parity():
    """Depth-4 parity for the HCE-backed variant searches (same pool,
    immediate eval): variant search trees must also be independent of
    which NNUE backend the pool was built with."""
    from fishnet_tpu.protocol.types import Variant

    weights = NnueWeights.random(seed=21)
    cases = [
        (Variant.ATOMIC, "rnbqkb1r/pppppppp/5n2/8/8/5N2/PPPPPPPP/RNBQKB1R w KQkq - 2 2"),
        (Variant.ANTICHESS, "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w - - 0 1"),
        (Variant.THREE_CHECK, "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"),
        (Variant.KING_OF_THE_HILL, "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"),
    ]
    results = {}
    for backend in ("scalar", "jax"):
        svc = SearchService(
            weights=weights, pool_slots=8, batch_capacity=64,
            tt_bytes=32 << 20, backend=backend,
        )
        try:
            out = []
            for variant, fen in cases:
                r = await svc.search(fen, [], depth=4, variant=variant)
                line = [l for l in r.lines if l.multipv == 1][-1]
                out.append((line.value, line.is_mate, r.best_move))
            results[backend] = out
        finally:
            svc.close()
    assert results["scalar"] == results["jax"]


@pytest.mark.slow
async def test_scalar_vs_jax_depth5_parity_bulk():
    """The heavyweight deep sweep (a thousand positions at depth 5)
    behind the `slow` marker; CI and local runs opt in with `-m slow`."""
    fens = _random_fens(1000, seed=555)
    weights = NnueWeights.random(seed=33)
    kw = dict(depth=5, tt_bytes=512 << 20, prefetch=8)
    scalar = await _parity_results("scalar", weights, fens, **kw)
    jax_out = await _parity_results("jax", weights, fens, **kw)
    mismatches = sum(1 for s, j in zip(scalar, jax_out) if s != j)
    assert mismatches == 0, f"{mismatches} of {len(fens)} positions diverged"


@pytest.mark.slow
async def test_scalar_vs_jax_depth1_parity_bulk():
    """The heavyweight sweep (a thousand positions) behind the `slow`
    marker; CI and local runs can opt in with `-m slow`."""
    fens = _random_fens(1000, seed=4242)
    weights = NnueWeights.random(seed=33)
    scalar = await _depth1_results("scalar", weights, fens)
    jax_out = await _depth1_results("jax", weights, fens)
    mismatches = sum(1 for s, j in zip(scalar, jax_out) if s != j)
    assert mismatches == 0, f"{mismatches} of {len(fens)} positions diverged"
