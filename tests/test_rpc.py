"""Split-plane RPC transport (doc/disaggregation.md): ring wraparound
and flow control at tiny FISHNET_RPC_RING_SLOTS, FISHNET_RPC_SLOT_BYTES
sizing failures, torn-record read-as-miss, stale-epoch refusal after a
frontend restart, demand timeout (FISHNET_RPC_TIMEOUT) and resubmit
after an evaluator rebirth, the ``rpc.detach`` fault site, the
FISHNET_RPC escape hatch (unset/"0" builds the monolith — the
supervisor's ``role=`` specs flip it per process), role federation
across scraped frontend/evaluator processes (FISHNET_RPC_DIR wiring),
and the two-process real smoke ``make rpc-smoke`` builds on: a
subprocess evaluator host serving a frontend ``RemoteBackend`` with
analyses bit-identical to a monolith. The full 3-frontend fleet with
SIGKILLs runs in ``bench.py --split``."""

import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

from fishnet_tpu.resilience import faults
from fishnet_tpu.rpc import rings
from fishnet_tpu.rpc.client import (
    EvaluatorLostError,
    RemoteBackend,
    _RpcClient,
)
from fishnet_tpu.rpc.host import EvaluatorHost

_REPO_ROOT = Path(__file__).resolve().parents[1]


def _delta(before: dict, key: str) -> int:
    return rings.stats().get(key, 0) - before.get(key, 0)


def _nnue_payload(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    feats = rng.integers(0, 1000, (n, 2, 32), dtype=np.uint16)
    buckets = rng.integers(0, 8, n, dtype=np.int32)
    parents = np.full(n, -1, np.int32)
    material = rng.integers(-100, 100, n, dtype=np.int32)
    return rings.pack_nnue_submit(feats, buckets, parents, material)


# -- transport units ---------------------------------------------------------


def test_ring_wraparound_and_flow_control(tmp_path, monkeypatch):
    """FISHNET_RPC_RING_SLOTS=2: records must survive many laps of the
    ring, and a producer outrunning the consumer must get RingFull —
    bounded blocking, never a clobbered slot."""
    monkeypatch.setenv(rings.RING_SLOTS_ENV, "2")
    front = rings.create_frontend_link(str(tmp_path), name="wrap.ring")
    host = rings.attach_host_link(front.path)
    try:
        for lap in range(7):  # > 3 full laps of a 2-slot ring
            payload = _nnue_payload(3, seed=lap)
            front.push(rings.KIND_NNUE_SUBMIT, lap + 1, 1, 3, payload)
            got = host.drain()
            assert len(got) == 1
            kind, ticket, epoch, n, back = got[0]
            assert (kind, ticket, epoch, n) == (
                rings.KIND_NNUE_SUBMIT, lap + 1, 1, 3,
            )
            assert back == payload
        # Fill both slots, then overflow within a short deadline.
        front.push(rings.KIND_NNUE_SUBMIT, 100, 1, 1, b"\0" * 8)
        front.push(rings.KIND_NNUE_SUBMIT, 101, 1, 1, b"\0" * 8)
        with pytest.raises(rings.RingFull):
            front.push(
                rings.KIND_NNUE_SUBMIT, 102, 1, 1, b"\0" * 8,
                deadline_s=0.05,
            )
        assert [t for _, t, _, _, _ in host.drain()] == [100, 101]
    finally:
        front.close()
        host.close()


def test_record_too_large_fails_loudly(tmp_path, monkeypatch):
    """A payload no slot can hold must raise RecordTooLarge (pointing
    at FISHNET_RPC_SLOT_BYTES), never truncate."""
    monkeypatch.setenv(rings.SLOT_BYTES_ENV, "256")
    front = rings.create_frontend_link(str(tmp_path), name="small.ring")
    try:
        assert front.slot_capacity == 256 - rings.REC_HEADER_BYTES
        with pytest.raises(rings.RecordTooLarge):
            front.push(rings.KIND_NNUE_SUBMIT, 1, 1, 8, b"\0" * 512)
    finally:
        front.close()


def test_torn_record_reads_as_miss(tmp_path):
    """A record whose payload was clobbered after publish (the
    SIGKILLed-writer shape) must fail the checksum and be SKIPPED —
    counted as torn, its slot consumed so the ring never wedges."""
    front = rings.create_frontend_link(str(tmp_path), name="torn.ring")
    host = rings.attach_host_link(front.path)
    before = rings.stats()
    try:
        payload = _nnue_payload(2)
        front.push(rings.KIND_NNUE_SUBMIT, 1, 1, 2, payload)
        # Corrupt one published payload byte in the mapped slot.
        front._submit[rings.REC_HEADER_BYTES] ^= 0xFF
        assert host.drain() == []
        assert _delta(before, "torn") == 1
        # The ring is not wedged: the next record flows.
        front.push(rings.KIND_NNUE_SUBMIT, 2, 1, 2, payload)
        got = host.drain()
        assert [t for _, t, _, _, _ in got] == [2]
        assert got[0][4] == payload
    finally:
        front.close()
        host.close()


def test_stale_epoch_refused_after_frontend_restart(tmp_path):
    """A restarted frontend bumps its epoch; the host must refuse the
    previous life's submit records (fencing) while serving the new
    ones."""
    first = rings.create_frontend_link(str(tmp_path), name="fe.ring")
    assert first.frontend_epoch == 1
    first.push(rings.KIND_NNUE_SUBMIT, 1, first.frontend_epoch, 2,
               _nnue_payload(2))
    first.close()  # SIGKILL: no unlink, the record is in the ring

    reborn = rings.create_frontend_link(str(tmp_path), name="fe.ring")
    assert reborn.frontend_epoch == 2
    reborn.push(rings.KIND_NNUE_SUBMIT, 2, reborn.frontend_epoch, 2,
                _nnue_payload(2))
    before = rings.stats()
    host = EvaluatorHost(rpc_dir=str(tmp_path))  # no backends needed
    try:
        host.sweep()
        assert _delta(before, "stale_refusals") == 1
        # The fresh-epoch record got past the fence (no NNUE backend
        # in this host, so it lands as unserviceable, not refused).
        assert _delta(before, "unserviceable") == 1
    finally:
        host.close()
        reborn.close()


def test_evaluator_death_demand_timeout_raises(tmp_path, monkeypatch):
    """No evaluator within FISHNET_RPC_TIMEOUT: the demand wait must
    surface EvaluatorLostError promptly (the service requeues the
    batch) — never hang."""
    monkeypatch.setenv(rings.TIMEOUT_ENV, "1")
    client = _RpcClient(str(tmp_path))
    try:
        payload = _nnue_payload(2)
        ticket = client.submit(rings.KIND_NNUE_SUBMIT, 2, payload)
        t0 = time.monotonic()
        with pytest.raises(EvaluatorLostError, match="requeue"):
            client.wait(ticket, 2, rings.KIND_NNUE_SUBMIT, payload)
        assert time.monotonic() - t0 < 10.0
    finally:
        client.close()


def test_evaluator_restart_resubmits_inflight_ticket(tmp_path):
    """Evaluator A consumes a submit record and dies unanswered; when
    evaluator B attaches (host-epoch bump), the waiting client must
    resubmit the kept payload and consume B's answer exactly once."""
    client = _RpcClient(str(tmp_path))
    before = rings.stats()
    try:
        payload = _nnue_payload(3, seed=9)
        ticket = client.submit(rings.KIND_NNUE_SUBMIT, 3, payload)

        host_a = rings.attach_host_link(client.link.path)
        rings.bump_host_epoch([host_a])
        assert len(host_a.drain()) == 1  # consumed, never answered
        host_a.close()  # death

        got = {}

        def waiter():
            got["res"] = client.wait(
                ticket, 3, rings.KIND_NNUE_SUBMIT, payload
            )

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.05)  # the wait observes epoch 1 first

        host_b = rings.attach_host_link(client.link.path)
        rings.bump_host_epoch([host_b])  # rebirth signal -> resubmit
        values = np.array([11, -22, 33], np.int32)
        deadline = time.monotonic() + 10.0
        served = False
        while not served and time.monotonic() < deadline:
            for kind, tkt, epoch, n, pay in host_b.drain():
                assert pay == payload  # self-contained resubmit
                host_b.push(
                    rings.KIND_NNUE_RESULT, tkt, epoch, n,
                    rings.pack_nnue_result(values),
                )
                served = True
            time.sleep(0.001)
        th.join(timeout=10.0)
        assert not th.is_alive() and served
        _kind, _n, result = got["res"]
        assert (rings.unpack_nnue_result(result, 3) == values).all()
        assert _delta(before, "resubmits") >= 1
        host_b.close()
    finally:
        client.close()


def test_rpc_detach_fault_site(tmp_path):
    """faults grammar ``rpc.detach``: the host drops one live link on
    the matched sweep (reason="fault", file kept) and re-attaches it on
    the next — the deterministic chaos hook bench.py --split scripts."""
    front = rings.create_frontend_link(str(tmp_path), name="fa.ring")
    host = EvaluatorHost(rpc_dir=str(tmp_path))
    before = rings.stats()
    faults.install("rpc.detach:nth=1:error")
    try:
        host.sweep()  # attaches, then the injected detach fires
        assert host._links == {}
        assert _delta(before, "detach.fault") == 1
        assert os.path.exists(front.path)  # fault detach keeps the file
        host.sweep()  # nth=1 already consumed: re-attach, keep serving
        assert len(host._links) == 1
        assert _delta(before, "attach.host") == 2
    finally:
        faults.clear()
        host.close()
        front.close()


# -- the escape hatch --------------------------------------------------------


def test_flag_off_builds_monolith_flag_on_builds_remote(monkeypatch):
    """FISHNET_RPC unset and "0" must keep the monolithic path (a plain
    SearchService — byte-for-byte the no-rpc build; the split parity
    itself is pinned by the two-process smoke below); "1" must route
    build_search_service to RemoteBackend."""
    from fishnet_tpu import __main__ as cli
    from fishnet_tpu.configure import Opt
    from fishnet_tpu.search.service import SearchService
    from fishnet_tpu.utils.logger import Logger

    monkeypatch.delenv("FISHNET_RPC", raising=False)
    assert not rings.rpc_enabled()
    monkeypatch.setenv("FISHNET_RPC", "0")
    assert not rings.rpc_enabled()

    opt = Opt(microbatch=64, pipeline=2, search_threads=1)
    logger = Logger(verbose=0)
    svc = cli.build_search_service(opt, logger)
    try:
        assert type(svc) is SearchService  # the monolith, not a shim
        assert not isinstance(svc, RemoteBackend)
    finally:
        svc.close()

    monkeypatch.setenv("FISHNET_RPC", "1")
    assert rings.rpc_enabled()

    class _Probe:
        def __init__(self, **kwargs):
            self.kwargs = kwargs

    import fishnet_tpu.rpc.client as client_mod

    monkeypatch.setattr(client_mod, "RemoteBackend", _Probe)
    probe = cli.build_search_service(opt, logger)
    assert isinstance(probe, _Probe)
    assert probe.kwargs["pipeline_depth"] == 2


# -- role federation ---------------------------------------------------------


def test_federation_distinct_proc_labels_for_roles():
    """The fleet aggregator must keep a frontend and an evaluator as
    distinct scraped procs, each with its role readable from
    fishnet_rpc_role (the console's ROLE column)."""
    from fishnet_tpu.telemetry.exporter import MetricsExporter
    from fishnet_tpu.telemetry.fleet import FleetAggregator, _role_of
    from fishnet_tpu.telemetry.registry import (
        MetricsRegistry,
        gauge_family,
    )

    def role_collector(role):
        def collect():
            return [gauge_family(
                "fishnet_rpc_role",
                "This process's split-plane role.",
                1,
                labels={"role": role},
            )]
        return collect

    reg_f = MetricsRegistry()
    reg_f.register_collector(role_collector("frontend"), name="rpc")
    reg_e = MetricsRegistry()
    reg_e.register_collector(role_collector("evaluator"), name="rpc")
    exp_f = MetricsExporter(port=0, registry=reg_f)
    exp_e = MetricsExporter(port=0, registry=reg_e)
    try:
        agg = FleetAggregator(
            targets={"F0": exp_f.url, "EVAL0": exp_e.url},
            poll_interval=60.0,
        )
        agg.poll_once()
        assert set(agg._procs) == {"F0", "EVAL0"}
        assert _role_of(agg._procs["F0"]) == "frontend"
        assert _role_of(agg._procs["EVAL0"]) == "evaluator"
    finally:
        exp_f.close()
        exp_e.close()


# -- two-process real smoke (make rpc-smoke's big brother) -------------------

_FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/4P3/5N2/PPPP1PPP/RNBQKB1R w KQkq - 2 3",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
]


def _analyses(svc):
    import asyncio

    svc.set_prefetch(0, adaptive=False)

    async def go():
        out = []
        for fen in _FENS:
            r = await svc.search(fen, [], nodes=160)
            out.append((
                r.best_move, r.depth, r.nodes,
                tuple((l.multipv, l.depth, l.is_mate, l.value,
                       tuple(l.pv)) for l in r.lines),
            ))
        return out

    return asyncio.run(go())


@pytest.mark.slow
def test_two_process_split_bit_identical_analyses(tmp_path, monkeypatch):
    """THE split-plane assertion: a frontend RemoteBackend served by a
    REAL subprocess evaluator host (different pid, own device context)
    must produce bit-identical analyses to an in-process monolith over
    the same weights. (The in-process twin of this parity — plus the
    3-frontend fused-fill and SIGKILL ledger gates — runs in bench.py
    --split.)"""
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.search.service import SearchService

    monkeypatch.setenv("FISHNET_NO_EVAL_CACHE", "1")
    weights = NnueWeights.random(seed=7)
    wpath = tmp_path / "w.nnue"
    weights.save(str(wpath))
    rpc_dir = tmp_path / "rpc"

    common = dict(
        weights=weights, pool_slots=8, batch_capacity=64,
        tt_bytes=8 << 20, backend="jax", psqt_path="host-material",
        pipeline_depth=2, driver_threads=1,
    )
    mono = SearchService(**common)
    mono_out = _analyses(mono)
    mono.close()

    env = dict(os.environ)
    env["PYTHONPATH"] = str(_REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    host = subprocess.Popen(
        [sys.executable, "-m", "fishnet_tpu.rpc.host",
         "--dir", str(rpc_dir), "--nnue-file", str(wpath),
         "--poll", "0.001"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        split = RemoteBackend(rpc_dir=str(rpc_dir), **common)
        split_out = _analyses(split)
        split.close()
    finally:
        host.terminate()
        try:
            host.wait(timeout=10)
        except subprocess.TimeoutExpired:
            host.kill()
            host.wait(timeout=10)
    assert host.returncode is not None
    assert split_out == mono_out, (
        "split-plane analyses diverged from the monolith"
    )
