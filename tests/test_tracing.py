"""Causal batch tracing (doc/observability.md "Causal tracing"): trace
contexts and ids, the fishnet-spans/2 record fields and dump locations,
trace-context propagation across the coalescer's pack/decode worker
handoffs (fused multi-owner fan-in included) — direct on the pipeline
and end-to-end through gated smokes, sync (FISHNET_NO_ASYNC=1) and
async — plus the critical-path analyzer (span-tree reconstruction,
orphan detection, wall-time attribution summing to the window), the
Chrome/Perfetto exporter with cross-thread flow arrows, and the
bench.py summary-schema contract. `make trace-smoke` runs this file."""

import json
import os
import threading
import time

import numpy as np
import pytest

from fishnet_tpu import telemetry
from fishnet_tpu.telemetry import critical_path as cp
from fishnet_tpu.telemetry import tracing
from fishnet_tpu.telemetry.spans import FORMAT, RECORDER, SpanRecorder
from fishnet_tpu.telemetry.trace_export import (
    chrome_trace,
    main as export_main,
    read_spans,
    validate_chrome_trace,
)
from fishnet_tpu.search.service import (
    _AsyncDispatchPipeline,
    _CoalesceTicket,
    _FusedValues,
)
from tests.test_async_dispatch import _SMOKE_FENS, _SlowValues, _smoke_run


@pytest.fixture
def tel_enabled():
    telemetry.enable()
    try:
        yield
    finally:
        telemetry.disable()


def _spans_since(t0):
    # spans() rounds t to 6 decimals — allow the round-down.
    return [s for s in RECORDER.spans() if s["t"] >= t0 - 1e-4]


# -- trace contexts and ids ---------------------------------------------------


def test_trace_context_chaining():
    root = tracing.new_trace()
    assert root.span_id == root.trace_id and root.parent_id is None
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    grandchild = child.child()
    assert grandchild.parent_id == child.span_id
    assert grandchild.trace_id == root.trace_id


def test_batch_trace_ids_deterministic():
    # Any stage knowing the batch id derives the same tree — no
    # registry: root span_id == trace_id, children parent to it.
    tid = tracing.trace_id_for_batch("wk0001")
    assert tid == tracing.trace_id_for_batch("wk0001")
    assert tid != tracing.trace_id_for_batch("wk0002")
    root = tracing.batch_root("wk0001")
    assert root.trace_id == root.span_id == tid and root.parent_id is None
    c1, c2 = tracing.batch_child("wk0001"), tracing.batch_child("wk0001")
    assert c1.trace_id == c2.trace_id == tid
    assert c1.parent_id == c2.parent_id == tid
    assert c1.span_id != c2.span_id


def test_span_ids_unique_across_threads():
    ids, lock = set(), threading.Lock()

    def mint():
        mine = {tracing.next_span_id() for _ in range(200)}
        with lock:
            ids.update(mine)

    threads = [threading.Thread(target=mint) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(ids) == 4 * 200


def test_links_for():
    ctxs = [tracing.new_trace() for _ in range(3)]
    links = tracing.links_for(ctxs)
    assert links == [(c.trace_id, c.span_id) for c in ctxs]


# -- fishnet-spans/2: record fields + dump locations --------------------------


def test_record_carries_trace_fields(tel_enabled):
    t0 = time.monotonic()
    root = tracing.new_trace()
    child = root.child()
    RECORDER.record("pack", t0, trace=root, group=0)
    RECORDER.record(
        "device_step", t0, trace=child,
        links=[("aaaa", "bbbb")], group=0,
    )
    spans = _spans_since(t0)
    by_stage = {s["stage"]: s for s in spans}
    pk = by_stage["pack"]
    assert pk["trace_id"] == pk["span_id"] == root.trace_id
    assert "parent_id" not in pk  # root: field omitted, not null
    ds = by_stage["device_step"]
    assert ds["trace_id"] == root.trace_id
    assert ds["parent_id"] == root.span_id
    assert ds["links"] == [["aaaa", "bbbb"]]


def test_dump_header_is_v2_and_spans_dir(tmp_path, monkeypatch):
    rec = SpanRecorder(capacity=8)
    # FISHNET_SPANS_DIR steers the per-pid dump file; the dir need not
    # pre-exist (dump() creates it).
    monkeypatch.delenv("FISHNET_SPANS_FILE", raising=False)
    monkeypatch.setenv("FISHNET_SPANS_DIR", str(tmp_path / "spans"))
    path = rec.default_path()
    assert path == str(
        tmp_path / "spans" / f"fishnet-spans-{os.getpid()}.jsonl"
    )
    rec.record("pack", time.monotonic(), trace=tracing.new_trace(), n=1)
    written = rec.dump(reason="test")
    assert written == path and os.path.exists(path)
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["format"] == FORMAT == "fishnet-spans/2"
    assert lines[1]["trace_id"] == lines[1]["span_id"]
    # FISHNET_SPANS_FILE wins outright.
    monkeypatch.setenv("FISHNET_SPANS_FILE", str(tmp_path / "exact.jsonl"))
    assert rec.default_path() == str(tmp_path / "exact.jsonl")


# -- span-tree reconstruction + critical-path attribution ---------------------


def _mk(stage, t, dur_ms, trace_id=None, span_id=None, parent_id=None,
        thread="t", **extra):
    s = {"stage": stage, "t": t, "dur_ms": dur_ms, "thread": thread}
    if trace_id:
        s["trace_id"] = trace_id
        s["span_id"] = span_id
        if parent_id:
            s["parent_id"] = parent_id
    s.update(extra)
    return s


def _synthetic_step_trace(base=100.0, tid="T1"):
    """A realistic async step trace: pack -> device_step ->
    dispatch_issue -> dispatch_wait -> wire_decode -> postprocess."""
    return [
        _mk("pack", base, 10.0, tid, tid),
        _mk("device_step", base + 0.010, 2.0, tid, "d", tid),
        _mk("dispatch_issue", base + 0.013, 2.0, tid, "i", "d",
            thread="dispatch-pack"),
        _mk("dispatch_wait", base + 0.015, 15.0, tid, "w", "i",
            thread="dispatch-decode"),
        _mk("wire_decode", base + 0.016, 15.0, tid, "wd", "w"),
        _mk("postprocess", base + 0.031, 4.0, tid, "pp", "wd"),
    ]


def test_critical_path_chain_follows_parents():
    spans = _synthetic_step_trace()
    chain = cp.critical_path(spans)
    assert [s["stage"] for s in chain] == [
        "pack", "device_step", "dispatch_issue", "dispatch_wait",
        "wire_decode", "postprocess",
    ]


def test_critical_path_group_traces_reattach_fan_in_links():
    # A fused dispatch shared by two step traces: parented under T1,
    # linked to T2 — group_traces re-attaches a copy under T2's link.
    spans = [
        _mk("pack", 0.0, 1.0, "T1", "T1"),
        _mk("pack", 0.0, 1.0, "T2", "T2"),
        _mk("device_step", 0.001, 1.0, "T1", "d1", "T1"),
        _mk("device_step", 0.001, 1.0, "T2", "d2", "T2"),
        _mk("dispatch_issue", 0.002, 1.0, "T1", "i", "d1",
            links=[["T2", "d2"]]),
    ]
    traces = cp.group_traces(spans)
    assert set(traces) == {"T1", "T2"}
    t2_issue = [s for s in traces["T2"] if s["stage"] == "dispatch_issue"]
    assert len(t2_issue) == 1
    assert t2_issue[0]["parent_id"] == "d2"
    assert "links" not in t2_issue[0]
    assert cp.orphan_spans(spans) == []


def test_critical_path_detects_orphans():
    spans = [
        _mk("pack", 0.0, 1.0, "T1", "T1"),
        _mk("device_step", 0.001, 1.0, "T1", "d", "missing-parent"),
    ]
    orphans = cp.orphan_spans(spans)
    assert len(orphans) == 1 and orphans[0]["stage"] == "device_step"


def test_fleet_joiner_adopts_orphans_from_killed_process():
    """The fleet stitcher (telemetry/stitch.py) feeds the SAME orphan
    detector: after joining a killed-and-reassigned unit whose dead
    actor lost a parent span to a missed scrape, the stitched output
    must be orphan-free — lost parents are adopted under the trace
    root and counted, never dropped."""
    from fishnet_tpu.telemetry.stitch import stitch
    from fishnet_tpu.telemetry.tracing import trace_id_for_batch

    tid = trace_id_for_batch("orphan-unit")
    # Dead actor: the batch root was never scraped (SIGKILL between
    # scrapes), leaving its child dangling.
    dead = [
        _mk("queue_wait", 1.0, 100.0, tid, "1.2", "lost-parent"),
    ]
    survivor = [
        _mk("acquire", 2.0, 50.0, tid, tid),
        _mk("submit", 2.2, 30.0, tid, "2.1", tid),
    ]
    report = stitch([
        {"proc": "P0", "actor": "P0@1", "spans": dead, "epoch_offset": 0.0},
        {"proc": "P1", "actor": "P1@2", "spans": survivor,
         "epoch_offset": 0.0},
    ])
    assert report["orphans_adopted"] >= 1
    assert report["reassignments"] == 1
    for trace in cp.group_traces(report["spans"]).values():
        assert cp.orphan_spans(trace) == []


def test_critical_path_attribution_sums_to_wall():
    attr = cp.attribute_trace(_synthetic_step_trace(), fixed_transport_ms=5.0)
    wall = attr["wall_ms"]
    assert wall == pytest.approx(35.0, abs=1e-6)
    total = sum(attr[c] for c in cp.COMPONENTS)
    assert total == pytest.approx(wall, rel=1e-9)
    # pack = pack + device_step; transport = issue span + 5 ms fixed
    # slice of the in-flight interval; the rest of [issue end, wait
    # end] is device compute; wire_decode's tail past the in-flight
    # interval is decode_wait; the device_step->issue gap is queueing.
    assert attr["pack"] == pytest.approx(12.0, abs=1e-6)
    assert attr["transport"] == pytest.approx(7.0, abs=1e-6)
    assert attr["device_compute"] == pytest.approx(10.0, abs=1e-6)
    assert attr["decode_wait"] == pytest.approx(1.0, abs=1e-6)
    assert attr["submit"] == pytest.approx(4.0, abs=1e-6)
    assert attr["queue_wait"] == pytest.approx(1.0, abs=1e-6)
    assert attr["other"] == pytest.approx(0.0, abs=1e-6)
    assert attr["coverage"] == pytest.approx(1.0, abs=1e-6)


def test_critical_path_report_aggregates_step_traces():
    spans = (
        _synthetic_step_trace(base=100.0, tid="T1")
        + _synthetic_step_trace(base=200.0, tid="T2")
    )
    rep = cp.report(spans, fixed_transport_ms=5.0, skip_warmup=False)
    assert rep["traces"] == 2
    assert rep["wall_ms"] == pytest.approx(35.0, abs=1e-3)
    assert rep["pack_ms"] == pytest.approx(12.0, abs=1e-3)
    assert rep["transport_ms"] == pytest.approx(7.0, abs=1e-3)
    assert rep["compute_ms"] == pytest.approx(10.0, abs=1e-3)
    assert rep["coverage"] >= 0.99
    # Empty input: zeroed shape, never a crash.
    empty = cp.report([])
    assert empty["traces"] == 0 and empty["wall_ms"] == 0.0


def test_critical_path_batch_report():
    tid = tracing.trace_id_for_batch("wkA")
    spans = [
        _mk("acquire", 0.0, 50.0, tid, tid),
        _mk("schedule", 0.051, 2.0, tid, "s", tid),
        _mk("queue_wait", 0.053, 200.0, tid, "q", tid),
        _mk("submit", 0.300, 40.0, tid, "sub", tid),
    ]
    rep = cp.batch_report(spans)
    assert rep["batches"] == 1
    assert rep["queue_wait_ms"] == pytest.approx(200.0, abs=1e-6)
    assert rep["submit_ms"] == pytest.approx(40.0, abs=1e-6)
    assert rep["schedule_ms"] == pytest.approx(52.0, abs=1e-6)
    assert rep["wall_ms"] == pytest.approx(340.0, abs=1e-3)


# -- Chrome/Perfetto export ---------------------------------------------------


def test_chrome_trace_export_structure_and_flow_arrows():
    trace = chrome_trace(_synthetic_step_trace())
    validate_chrome_trace(trace)
    events = trace["traceEvents"]
    x = [e for e in events if e["ph"] == "X"]
    m = [e for e in events if e["ph"] == "M"]
    assert len(x) == 6
    # One track per recording thread.
    assert {e["args"]["name"] for e in m} == {
        "t", "dispatch-pack", "dispatch-decode",
    }
    # Cross-thread causal edges render as s/f flow pairs: driver ->
    # pack worker, pack -> decode worker, decode -> driver.
    starts = [e for e in events if e["ph"] == "s"]
    finishes = [e for e in events if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 3
    assert all(e["bp"] == "e" for e in finishes)
    # Same-thread parent links (pack -> device_step) emit NO arrow.
    ids = {e["id"] for e in starts}
    assert len(ids) == 3


def test_chrome_trace_export_validation_rejects_malformed():
    trace = chrome_trace(_synthetic_step_trace())
    bad = json.loads(json.dumps(trace))
    bad["traceEvents"][1].pop("tid", None)
    with pytest.raises(ValueError):
        validate_chrome_trace(bad)
    with pytest.raises(ValueError):
        validate_chrome_trace({"nope": []})
    # A dangling flow start must fail, not render as a broken arrow.
    dangling = json.loads(json.dumps(trace))
    dangling["traceEvents"] = [
        e for e in dangling["traceEvents"] if e["ph"] != "f"
    ]
    with pytest.raises(ValueError):
        validate_chrome_trace(dangling)


def test_trace_export_cli_roundtrip(tmp_path, capsys):
    # Two dumps of the same ring (overlapping contents, one header
    # each): read_spans must skip headers and de-duplicate.
    spans = _synthetic_step_trace()
    dump = tmp_path / "fishnet-spans-1.jsonl"
    with open(dump, "w") as fp:
        for seq in (1, 2):
            fp.write(json.dumps({
                "format": FORMAT, "seq": seq, "reason": "test",
                "pid": 1, "dumped_at": 0.0, "monotonic_to_epoch": 0.0,
                "spans": len(spans),
            }) + "\n")
            for s in spans:
                fp.write(json.dumps(s) + "\n")
    assert len(read_spans([str(dump)])) == len(spans)
    out = tmp_path / "trace.json"
    assert export_main([str(dump), "-o", str(out)]) == 0
    trace = json.loads(out.read_text())
    validate_chrome_trace(trace)
    assert sum(1 for e in trace["traceEvents"] if e["ph"] == "X") == len(spans)


# -- propagation across the pack/decode worker handoff (direct) ---------------


class _StubCoalescer:
    def _execute(self, tickets, defer_cost=False):
        for tk in tickets:
            tk.done.set()


class _StubSvc:
    def __init__(self):
        self._coalescer = _StubCoalescer()


def test_handoff_propagation_fused_multi_owner(tel_enabled):
    """The tentpole invariant, pinned directly on the pipeline: one
    fused dispatch owned by TWO step traces. dispatch_issue parents
    under the FIRST owner's device_step context and links the second;
    dispatch_wait (decode worker, a second thread handoff) chains under
    dispatch_issue in the same trace, links preserved."""
    d1 = tracing.new_trace().child()  # two owners' device_step contexts
    d2 = tracing.new_trace().child()
    t0 = time.monotonic()
    pipe = _AsyncDispatchPipeline(_StubSvc())
    try:
        tks = [
            _CoalesceTicket(0, 1, 4, trace=d1),
            _CoalesceTicket(1, 1, 4, trace=d2),
        ]
        tks[0].values = _FusedValues(np.zeros(8, np.int32))
        assert pipe.submit(tks)
        for tk in tks:
            assert tk.done.wait(5) and tk.error is None
        deadline = time.monotonic() + 5
        while (
            "dispatch_wait" not in {s["stage"] for s in _spans_since(t0)}
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
    finally:
        pipe.close()
    by_stage = {s["stage"]: s for s in _spans_since(t0)}
    issue, wait = by_stage["dispatch_issue"], by_stage["dispatch_wait"]
    assert issue["trace_id"] == d1.trace_id
    assert issue["parent_id"] == d1.span_id
    assert issue["links"] == [[d2.trace_id, d2.span_id]]
    assert issue["thread"] == "dispatch-pack"
    assert wait["trace_id"] == d1.trace_id  # identical across the handoff
    assert wait["parent_id"] == issue["span_id"]
    assert wait["links"] == issue["links"]
    assert wait["thread"] == "dispatch-decode"
    # Reconstructed: both owners' traces see the shared spans, orphan-free.
    spans = [
        s for s in _spans_since(t0)
        if s.get("trace_id") in (d1.trace_id, d2.trace_id)
    ]
    traces = cp.group_traces(spans)
    assert {s["stage"] for s in traces[d2.trace_id]} >= {
        "dispatch_issue", "dispatch_wait",
    }


def test_decode_queue_depth_gauge_direct():
    pipe = _AsyncDispatchPipeline(_StubSvc())
    try:
        assert pipe.decode_queue_depth() == 0
    finally:
        pipe.close()
    from fishnet_tpu.search.service import _COUNTER_METRICS

    name, kind, _ = _COUNTER_METRICS["decode_queue"]
    assert name == "fishnet_decode_queue_depth" and kind == "gauge"


# -- end-to-end gated smokes --------------------------------------------------


def _slow_mutate(svc):
    # Transport-like materialization latencies (test_async_dispatch's
    # overlap idiom) so in-flight intervals are visible in the trees.
    orig_seg = svc._dispatch_segmented
    orig_solo = svc._dispatch_eval

    def slow_segmented(tickets):
        orig_seg(tickets)
        fv = tickets[0].values
        fv._arr = _SlowValues(fv._arr, 0.02)

    def slow_solo(group, n, rows):
        values, acct = orig_solo(group, n, rows)
        return _SlowValues(values, 0.02), acct

    svc._dispatch_segmented = slow_segmented
    svc._dispatch_eval = slow_solo


def _step_traces(spans):
    return {
        tid: sp for tid, sp in cp.group_traces(spans).items()
        if any(s["stage"] == "pack" for s in sp)
    }


def test_trace_smoke_async(monkeypatch, tel_enabled):
    """Acceptance smoke, async path: every eval microbatch yields a
    complete span tree (zero orphans) spanning the driver -> pack ->
    decode thread handoffs, the Chrome export validates with flow
    arrows, and critical-path attribution covers >= 95% of steady-state
    per-batch wall time."""
    from fishnet_tpu.nnue.weights import NnueWeights

    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "2")
    t0 = time.monotonic()
    _, _, meta = _smoke_run(
        NnueWeights.random(seed=7), fens=_SMOKE_FENS[:4], nodes=150,
        mutate=_slow_mutate,
    )
    assert meta["async"]
    spans = _spans_since(t0)
    stages = {s["stage"] for s in spans}
    assert stages >= {
        "pack", "device_step", "dispatch_issue", "dispatch_wait",
        "wire_decode", "postprocess",
    }
    traced = [s for s in spans if "trace_id" in s]
    assert cp.orphan_spans(traced) == [], "orphan spans in a gated run"
    step = _step_traces(traced)
    assert len(step) > 3
    for tid, sp in step.items():
        roots = [s for s in sp if s["stage"] == "pack"]
        assert len(roots) == 1 and roots[0]["span_id"] == tid
        assert {s["stage"] for s in sp} >= {
            "pack", "device_step", "wire_decode", "postprocess",
        }
    # The async handoff spans land in >= 3 distinct threads per fused
    # trace: driver, dispatch-pack, dispatch-decode.
    threads = {
        s["thread"] for sp in step.values() for s in sp
        if s["stage"] in ("device_step", "dispatch_issue", "dispatch_wait")
    }
    assert {"dispatch-pack", "dispatch-decode"} <= threads
    # Critical-path attribution: >= 95% of steady-state wall attributed.
    rep = cp.report(traced)
    assert rep["traces"] > 0
    assert rep["coverage"] >= 0.95, rep
    total = sum(
        rep[k] for k in (
            "queue_wait_ms", "pack_ms", "transport_ms", "compute_ms",
            "decode_wait_ms", "submit_ms", "other_ms",
        )
    )
    assert total == pytest.approx(rep["wall_ms"], rel=0.05)
    # Perfetto export of the same spans: valid, with handoff arrows.
    trace = chrome_trace(spans)
    validate_chrome_trace(trace)
    assert any(e["ph"] == "s" for e in trace["traceEvents"])


def test_trace_smoke_sync(monkeypatch, tel_enabled):
    """FISHNET_NO_ASYNC=1: the same complete-tree and coverage
    guarantees hold on the inline synchronous flush (no
    dispatch_issue/dispatch_wait spans, no worker threads)."""
    from fishnet_tpu.nnue.weights import NnueWeights

    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "2")
    monkeypatch.setenv("FISHNET_NO_ASYNC", "1")
    t0 = time.monotonic()
    _, _, meta = _smoke_run(
        NnueWeights.random(seed=7), fens=_SMOKE_FENS[:4], nodes=150,
    )
    assert not meta["async"]
    traced = [s for s in _spans_since(t0) if "trace_id" in s]
    assert cp.orphan_spans(traced) == []
    step = _step_traces(traced)
    assert len(step) > 3
    for tid, sp in step.items():
        assert {s["stage"] for s in sp} >= {
            "pack", "device_step", "wire_decode", "postprocess",
        }
    rep = cp.report(traced)
    assert rep["traces"] > 0 and rep["coverage"] >= 0.95, rep


def test_trace_smoke_decode_queue_counter(monkeypatch):
    """The output-side backlog gauge rides counters() on both paths."""
    from fishnet_tpu.nnue.weights import NnueWeights

    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "2")
    _, counters, meta = _smoke_run(
        NnueWeights.random(seed=3), fens=_SMOKE_FENS[:2], nodes=100,
    )
    assert meta["async"] and counters["decode_queue"] >= 0
    monkeypatch.setenv("FISHNET_NO_ASYNC", "1")
    _, counters, _ = _smoke_run(
        NnueWeights.random(seed=3), fens=_SMOKE_FENS[:2], nodes=100,
    )
    assert counters["decode_queue"] == 0


# -- bench summary schema -----------------------------------------------------


def _fake_summary():
    from bench import SUMMARY_SCHEMA

    s = {k: 0 for k in SUMMARY_SCHEMA["top"]}
    s["traffic"] = {
        "overlap": {k: 0 for k in SUMMARY_SCHEMA["traffic.overlap"]}
    }
    s["critical_path"] = {k: 0 for k in SUMMARY_SCHEMA["critical_path"]}
    return s


def test_bench_summary_schema_export():
    """The single stdout JSON line's schema is a pinned contract: both
    the overlap report and the critical-path attribution ride it, and
    emit_summary refuses a summary missing any promised key."""
    from bench import validate_summary

    validate_summary(_fake_summary())
    for missing in ("critical_path", "dispatch_overlap_ratio"):
        broken = _fake_summary()
        del broken[missing]
        with pytest.raises(ValueError, match=missing):
            validate_summary(broken)
    nested = _fake_summary()
    del nested["critical_path"]["compute_ms"]
    with pytest.raises(ValueError, match="critical_path.compute_ms"):
        validate_summary(nested)
    overlap_broken = _fake_summary()
    del overlap_broken["traffic"]["overlap"]["overlap_ratio"]
    with pytest.raises(ValueError, match="overlap_ratio"):
        validate_summary(overlap_broken)


def test_bench_critical_path_report_fn(tel_enabled):
    from bench import critical_path_report_from_spans

    rep = critical_path_report_from_spans(fixed_transport_ms=5.0)
    assert set(rep) >= {"wall_ms", "coverage", "traces", "compute_ms"}
