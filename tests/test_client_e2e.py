"""End-to-end client tests against the fake lichess server: acquire ->
validate -> expand -> analyse (mock engine) -> reassemble -> submit."""

import asyncio

import pytest

from fishnet_tpu.client import Client
from fishnet_tpu.engine.mock import MockEngineFactory
from fishnet_tpu.sched.queue import BacklogOpt
from fishnet_tpu.utils.logger import Logger
from tests.fake_server import VALID_KEY, FakeServer

pytestmark = pytest.mark.anyio


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return False


def make_client(endpoint, cores=2, **kwargs) -> Client:
    return Client(
        endpoint=endpoint,
        key=VALID_KEY,
        cores=cores,
        engine_factory=kwargs.pop("engine_factory", MockEngineFactory()),
        logger=Logger(verbose=0),
        max_backoff=kwargs.pop("max_backoff", 0.2),
        **kwargs,
    )


async def test_analysis_batch_end_to_end():
    async with FakeServer() as server:
        moves = "e2e4 c7c5 c2c4 b8c6 g1e2 g8f6 b1c3 c6b4 g2g3 b4d3"
        work_id = server.lichess.add_analysis_job(moves=moves, skip_positions=[1, 4])
        client = make_client(server.endpoint)
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()

        body = server.lichess.analyses[work_id]
        assert body["fishnet"]["apikey"] == VALID_KEY
        assert body["stockfish"]["flavor"] == "nnue"
        parts = body["analysis"]
        assert len(parts) == 11  # root + 10 plies
        assert parts[1] == {"skipped": True}
        assert parts[4] == {"skipped": True}
        for i, part in enumerate(parts):
            assert part is not None
            if i not in (1, 4):
                assert "score" in part and "depth" in part and "nodes" in part


async def test_move_job_end_to_end():
    async with FakeServer() as server:
        work_id = server.lichess.add_move_job(
            moves="e2e4", level=5, clock={"wtime": 18000, "btime": 18000, "inc": 2}
        )
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.moves)
        await client.stop()
        best = server.lichess.moves[work_id]["move"]["bestmove"]
        assert isinstance(best, str) and len(best) >= 4


async def test_all_skipped_batch_completes_immediately():
    async with FakeServer() as server:
        work_id = server.lichess.add_analysis_job(
            moves="e2e4", skip_positions=[0, 1]
        )
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()
        parts = server.lichess.analyses[work_id]["analysis"]
        assert parts == [{"skipped": True}, {"skipped": True}]


async def test_invalid_batch_ignored():
    async with FakeServer() as server:
        bad = server.lichess.add_analysis_job(moves="e2e4 e2e4")  # illegal replay
        good = server.lichess.add_analysis_job(moves="d2d4")
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(lambda: good in server.lichess.analyses)
        await client.stop()
        assert bad not in server.lichess.analyses


async def test_engine_failure_abandons_batch_silently():
    async with FakeServer() as server:
        # Fail while analysing ply 3 of the doomed batch.
        doomed = server.lichess.add_analysis_job(moves="e2e4 e7e5 g1f3")
        survivor = server.lichess.add_analysis_job(moves="d2d4")
        factory = MockEngineFactory(fail_on="#3")
        client = make_client(server.endpoint, cores=1, engine_factory=factory)
        await client.start()
        assert await wait_for(lambda: survivor in server.lichess.analyses)
        await client.stop()
        # The doomed batch is neither submitted nor aborted: the server
        # reassigns it by timeout (reference queue.rs:207-214).
        assert doomed not in server.lichess.analyses
        assert doomed not in server.lichess.aborted


async def test_rejected_acquire_stops_queue():
    async with FakeServer() as server:
        server.lichess.reject_with = 406
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(lambda: server.lichess.acquire_count >= 1)
        # Queue stops on its own; acquire count must not keep growing.
        await asyncio.sleep(0.3)
        count = server.lichess.acquire_count
        await asyncio.sleep(0.3)
        assert server.lichess.acquire_count == count
        await client.stop()


async def test_shutdown_aborts_pending_batches():
    async with FakeServer() as server:
        work_id = server.lichess.add_analysis_job(
            moves="e2e4 e7e5 g1f3 b8c6 f1b5 a7a6 b5a4 g8f6"
        )
        # Slow engine so the batch is still pending at shutdown.
        factory = MockEngineFactory(delay_seconds=0.5)
        client = make_client(server.endpoint, cores=1, engine_factory=factory)
        await client.start()
        assert await wait_for(lambda: server.lichess.acquire_count >= 1)
        await asyncio.sleep(0.1)  # let the batch enter pending
        await client.stop(abort_pending=True)
        assert work_id in server.lichess.aborted
        assert work_id not in server.lichess.analyses


async def test_progress_reports_sent_with_null_first_part():
    async with FakeServer() as server:
        moves = " ".join(
            "e2e4 e7e5 g1f3 b8c6 f1b5 a7a6 b5a4 g8f6 e1h1 f8e7 f1e1 b7b5 a4b3 d7d6".split()
        )
        work_id = server.lichess.add_analysis_job(moves=moves)
        factory = MockEngineFactory(delay_seconds=0.01)
        client = make_client(server.endpoint, cores=1, engine_factory=factory)
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()
        reports = server.lichess.progress_reports.get(work_id, [])
        assert reports, "expected at least one progress report"
        for report in reports:
            assert report["analysis"][0] is None


async def test_multipv_matrix_submission():
    async with FakeServer() as server:
        work_id = server.lichess.add_analysis_job(moves="e2e4", multipv=3, depth=14)
        client = make_client(server.endpoint, cores=1)
        await client.start()
        assert await wait_for(lambda: work_id in server.lichess.analyses)
        await client.stop()
        parts = server.lichess.analyses[work_id]["analysis"]
        part = parts[0]
        assert isinstance(part["pv"], list)  # matrix form: multipv x depth
        assert isinstance(part["score"], list)
        assert len(part["score"]) == 3
        # No progress reports for matrix batches (queue.rs:286-288).
        assert work_id not in server.lichess.progress_reports


async def test_key_check():
    from fishnet_tpu.net.api import channel

    async with FakeServer() as server:
        logger = Logger()
        stub, actor = channel(server.endpoint, VALID_KEY, logger)
        task = asyncio.create_task(actor.run())
        assert await stub.check_key() is None
        actor.stop()
        await asyncio.wait_for(task, 5)

        stub2, actor2 = channel(server.endpoint, "WRONGKEY", logger)
        task2 = asyncio.create_task(actor2.run())
        err = await stub2.check_key()
        assert err is not None
        actor2.stop()
        await asyncio.wait_for(task2, 5)


async def test_variant_batch_analyzed_with_hce_flavor():
    # Variant batches route to the MULTI_VARIANT flavor (HCE eval, like the
    # reference's Fairy-Stockfish tier) and complete alongside standard work.
    async with FakeServer() as server:
        variant_job = server.lichess.add_analysis_job(moves="e2e4", variant="atomic")
        standard_job = server.lichess.add_analysis_job(moves="e2e4")
        client = make_client(server.endpoint, cores=2)
        await client.start()
        assert await wait_for(
            lambda: variant_job in server.lichess.analyses
            and standard_job in server.lichess.analyses
        )
        await client.stop()
        assert server.lichess.analyses[variant_job]["stockfish"]["flavor"] == "classical"
        assert server.lichess.analyses[standard_job]["stockfish"]["flavor"] == "nnue"
        plies = server.lichess.analyses[variant_job]["analysis"]
        assert all("pv" in p for p in plies)


async def test_workers_analyze_batch_concurrently():
    """The TPU-native worker model: `workers` pull loops over one shared
    service analyze a batch's positions CONCURRENTLY — a 10-position
    batch with a 0.3s-per-position engine completes in ~one position's
    latency x ceil(10/8), not 10 serial delays (the reference's
    one-engine-per-core model can't do this; our engine is a slot in a
    shared pool)."""
    import time

    from fishnet_tpu.engine.mock import MockEngineFactory

    moves = "e2e4 e7e5 g1f3 b8c6 f1b5 a7a6 b5a4 g8f6 e1g1"
    async with FakeServer() as server:
        work_id = server.lichess.add_analysis_job(moves=moves)
        client = make_client(
            server.endpoint, cores=1, workers=8,
            engine_factory=MockEngineFactory(delay_seconds=0.3),
        )
        await client.start()
        t0 = time.monotonic()
        assert await wait_for(
            lambda: work_id in server.lichess.analyses, timeout=15
        )
        elapsed = time.monotonic() - t0
        await client.stop()
        parts = server.lichess.analyses[work_id]["analysis"]
        assert len([p for p in parts if p]) == 10
        # Serial would be >= 3.0s of engine delay alone; 8-way
        # concurrency needs 2 waves (0.6s) plus overhead.
        assert elapsed < 2.4, f"batch took {elapsed:.1f}s — workers serialized?"
