"""Fleet-wide position tier (doc/eval-cache.md "Fleet tier"): segment
units (NNUE int32 + AZ fp16 round-trips, owner scoping, fingerprint
isolation), the graceful attach-fallback ladder, torn-slot safety under
real multi-process writers, SIGKILL-while-writing recovery (slot
reclaim), and the two-process cross-process-hit smoke that ``make
fleet-cache-smoke`` gates on. The full 3-process supervisor fleet with
a mid-replay SIGKILL runs in ``bench.py --fleet-cache``."""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

from fishnet_tpu.cluster import position_tier
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.resilience.faults import FaultPlan
from fishnet_tpu.search import eval_cache

_REPO_ROOT = Path(__file__).resolve().parents[1]


def _val_of(key: int) -> int:
    """Deterministic value-from-key: ANY value a reader accepts can be
    checked against its key, so a torn or interleaved write that slips
    past the seqlock+checksum would be caught as a wrong value."""
    return int((key * 2654435761) & 0x7FFFFFFF) - (1 << 30)


@pytest.fixture
def tier_env(tmp_path, monkeypatch):
    seg = tmp_path / "tier.seg"
    monkeypatch.setenv("FISHNET_POSITION_TIER", "1")
    monkeypatch.setenv("FISHNET_POSITION_TIER_PATH", str(seg))
    monkeypatch.setenv("FISHNET_POSITION_TIER_CAPACITY", "4096")
    monkeypatch.setenv("FISHNET_POSITION_TIER_AZ_CAPACITY", "32")
    position_tier.reset_tier()
    yield seg
    position_tier.reset_tier()


# -- units ------------------------------------------------------------------


def test_tier_nnue_roundtrip_exact_and_owner_scope(tier_env):
    tier = position_tier.get_tier()
    assert tier is not None
    keys = np.array([0x1234, 0x9876, 0xDEADBEEF], dtype=np.uint64)
    vals = np.array([17, -250, 31000], dtype=np.int32)
    tier.insert_nnue_block(keys, vals)
    out = np.zeros(3, np.int32)
    mask = np.zeros(3, bool)
    assert tier.probe_nnue_block(keys, out, mask) == 3
    assert mask.all() and (out == vals).all(), "int32 evals must be exact"
    # Rows already filled (mask set) are never re-probed or clobbered.
    out2 = np.array([111, 0, 0], np.int32)
    mask2 = np.array([True, False, False])
    assert tier.probe_nnue_block(keys, out2, mask2) == 2
    assert out2[0] == 111
    st = position_tier.stats()
    # Same pid wrote the slots -> hits are scope=local, not fleet.
    assert st.get("hits.local.nnue", 0) >= 5
    assert st.get("hits.fleet.nnue", 0) == 0


def test_tier_az_roundtrip_exact_fp16(tier_env):
    tier = position_tier.get_tier()
    policy = (
        np.random.RandomState(3)
        .randn(position_tier.AZ_POLICY_SIZE)
        .astype(np.float16)
    )
    tier.insert_az(0x777, policy, 0.125)
    got = tier.probe_az(0x777)
    assert got is not None
    gpol, gval = got
    assert gval == 0.125
    assert gpol.dtype == np.float16 and (gpol == policy).all(), (
        "fp16 policy payload must round-trip bit-exact"
    )
    assert tier.probe_az(0x778) is None


def test_tier_fingerprint_mismatch_isolation(tier_env):
    """Keys are salted ``zobrist ^ net_fingerprint`` BY THE CALLER, so
    two processes serving different nets key disjoint regions: net B
    never reads net A's evals for the same position."""
    tier = position_tier.get_tier()
    zobrist = 0xABCDEF0123456789
    fp_a, fp_b = 0x1111, 0x2222
    tier.insert_nnue_block(
        np.array([zobrist ^ fp_a], np.uint64), np.array([555], np.int32)
    )
    out = np.zeros(1, np.int32)
    mask = np.zeros(1, bool)
    assert tier.probe_nnue_block(
        np.array([zobrist ^ fp_b], np.uint64), out, mask
    ) == 0
    assert not mask[0]
    mask[:] = False
    assert tier.probe_nnue_block(
        np.array([zobrist ^ fp_a], np.uint64), out, mask
    ) == 1
    assert out[0] == 555


def test_tier_generation_clock_shared(tier_env):
    tier = position_tier.get_tier()
    g0 = tier.generation()
    tier.advance_generation()
    # A second attach of the same segment sees the tick: the clock
    # lives in the shared header, not in any process.
    position_tier.reset_tier()
    tier2 = position_tier.get_tier()
    assert tier2.generation() == g0 + 1


def test_tier_disabled_and_absent_fallbacks(tmp_path, monkeypatch):
    # Env off -> no tier, no segment file created.
    monkeypatch.setenv("FISHNET_POSITION_TIER", "0")
    position_tier.reset_tier()
    assert position_tier.get_tier() is None
    # Env on but the path is unwritable -> graceful local fallback.
    monkeypatch.setenv("FISHNET_POSITION_TIER", "1")
    monkeypatch.setenv(
        "FISHNET_POSITION_TIER_PATH", str(tmp_path / "no" / "such" / "dir/x")
    )
    position_tier.reset_tier()
    before = position_tier.stats().get("attach.local", 0)
    assert position_tier.get_tier() is None
    assert position_tier.stats().get("attach.local", 0) == before + 1
    position_tier.reset_tier()


def test_tier_corrupt_segment_rejected(tmp_path, monkeypatch):
    """A file that isn't a tier segment (foreign magic) must fall back
    to process-local, never be reinterpreted as slots."""
    seg = tmp_path / "garbage.seg"
    seg.write_bytes(b"\x00" * 64 + os.urandom(8192))
    monkeypatch.setenv("FISHNET_POSITION_TIER", "1")
    monkeypatch.setenv("FISHNET_POSITION_TIER_PATH", str(seg))
    position_tier.reset_tier()
    assert position_tier.get_tier() is None
    position_tier.reset_tier()


# -- multi-process torn-slot safety -----------------------------------------

# Writer child: hammers an overlapping key range with values derived
# from the key (``_val_of``), so the parent can verify EVERY hit it
# reads while the writers race. numpy-only — no jax import cost.
_WRITER = r"""
import os, sys
import numpy as np
from fishnet_tpu.cluster import position_tier as pt

base, n, rounds = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
tier = pt.get_tier()
assert tier is not None, "writer failed to attach"
keys = np.array(
    [((base + i) * 0x9E3779B97F4A7C15) & ((1 << 64) - 1) or 1
     for i in range(n)],
    dtype=np.uint64,
)
vals = np.array(
    [int((int(k) * 2654435761) & 0x7FFFFFFF) - (1 << 30) for k in keys],
    dtype=np.int32,
)
print("ready", flush=True)
for _ in range(rounds):
    tier.insert_nnue_block(keys, vals)
print("done", flush=True)
"""


def _spawn_writer(base: int, n: int, rounds: int) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(_REPO_ROOT)
    return subprocess.Popen(
        [sys.executable, "-c", _WRITER, str(base), str(n), str(rounds)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def test_tier_multiprocess_writers_never_serve_torn_values(tier_env):
    """Two real writer processes hammering an overlapping window while
    this process reads continuously: every hit must carry the value
    derived from its key — a torn read or an interleaved write must
    surface as a miss (seqlock/checksum reject), never a wrong value —
    and hits against sibling-written slots must count scope=fleet."""
    n, rounds = 64, 200
    writers = [_spawn_writer(0, n, rounds), _spawn_writer(0, n, rounds)]
    try:
        tier = position_tier.get_tier()
        keys = np.array(
            [(i * 0x9E3779B97F4A7C15) & ((1 << 64) - 1) or 1
             for i in range(n)],
            dtype=np.uint64,
        )
        expected = np.array([_val_of(int(k)) for k in keys], np.int32)
        out = np.zeros(n, np.int32)
        deadline = time.monotonic() + 20.0
        total_hits = 0
        while time.monotonic() < deadline:
            mask = np.zeros(n, bool)
            hits = tier.probe_nnue_block(keys, out, mask)
            if hits:
                total_hits += hits
                assert (out[mask] == expected[mask]).all(), (
                    "tier served a value inconsistent with its key"
                )
            if all(w.poll() is not None for w in writers):
                break
        for w in writers:
            stdout, stderr = w.communicate(timeout=30)
            assert w.returncode == 0, stderr
            assert "done" in stdout
        # Final sweep: the settled segment serves the full window.
        mask = np.zeros(n, bool)
        assert tier.probe_nnue_block(keys, out, mask) == n
        assert (out == expected).all()
        assert position_tier.stats().get("hits.fleet.nnue", 0) > 0, (
            "sibling-written slots must count as fleet-scope hits"
        )
    finally:
        for w in writers:
            if w.poll() is None:
                w.kill()
                w.communicate()


def test_tier_sigkill_while_writing_recovers(tier_env):
    """SIGKILL a writer mid-flight (fired through the chaos fault-plan
    grammar, ``proc.kill`` — the same site the fleet supervisor polls):
    the survivor must read only key-consistent values, and a later
    writer must reclaim any slot the victim left mid-write (odd seq)."""
    plan = FaultPlan.parse("seed=3;proc.kill:nth=3:crash")
    n = 64
    victim = _spawn_writer(0, n, 100_000)
    assert victim.stdout.readline().strip() == "ready"
    while True:  # the supervisor's per-tick poll, verbatim
        time.sleep(0.02)
        if plan.poll("proc.kill") is not None:
            victim.send_signal(signal.SIGKILL)
            break
    victim.communicate()
    assert victim.returncode == -signal.SIGKILL

    tier = position_tier.get_tier()
    keys = np.array(
        [(i * 0x9E3779B97F4A7C15) & ((1 << 64) - 1) or 1 for i in range(n)],
        dtype=np.uint64,
    )
    expected = np.array([_val_of(int(k)) for k in keys], np.int32)
    out = np.zeros(n, np.int32)
    mask = np.zeros(n, bool)
    hits = tier.probe_nnue_block(keys, out, mask)
    assert (out[mask] == expected[mask]).all(), "post-kill torn value"
    # Reclaim: re-inserting the full window must make every key
    # probeable again, including any slot killed mid-write.
    tier.insert_nnue_block(keys, expected)
    mask = np.zeros(n, bool)
    assert tier.probe_nnue_block(keys, out, mask) == n, (
        f"dead writer's slots not reclaimed (first pass served {hits})"
    )
    assert (out == expected).all()


# -- service integration (one pid, fleet shape) -----------------------------


def test_service_fleet_tier_parity_and_reuse(tier_env, monkeypatch):
    """The supervisor-respawn shape in one process: run A populates the
    segment, the process cache dies (reset), run B warm-starts off the
    TIER — analyses bit-identical to tier-off, pre-wire hits > 0,
    fewer dispatches than the cold run. Also pins satellite wiring:
    tier hits ride the same hmask the provide-time fc_pool_tt_fill
    loop consumes, so parity here covers the TT back-fill path too."""
    from test_eval_cache import _smoke

    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_POSITION_TIER", "0")
    position_tier.reset_tier()
    eval_cache.reset_cache()
    off, c_off = _smoke(weights)

    monkeypatch.setenv("FISHNET_POSITION_TIER", "1")
    position_tier.reset_tier()
    eval_cache.reset_cache()
    cold, c_cold = _smoke(weights)
    assert cold == off, "tier-on cold run changed analysis output"

    eval_cache.reset_cache()  # process death; the segment survives
    warm, c_warm = _smoke(weights)
    assert warm == off, "tier-warmed run changed analysis output"
    assert c_warm["cache_prewire_hits"] > 0
    assert c_warm["dispatches"] < c_cold["dispatches"], (
        c_warm["dispatches"], c_cold["dispatches"],
    )
    assert position_tier.stats().get("hits.local.nnue", 0) > 0
    eval_cache.reset_cache()


# -- two-process cross-process-hit smoke (make fleet-cache-smoke) -----------

# Driver child: a real SearchService run against the shared segment,
# emitting (analyses, tier stats) as one JSON line. Sequential
# submissions keep the schedule deterministic across processes.
_DRIVER = r"""
import asyncio, json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import SearchService
from fishnet_tpu.cluster import position_tier

FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/4P3/5N2/PPPP1PPP/RNBQKB1R w KQkq - 2 3",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "4rrk1/pp1n3p/3q2pQ/2p1pb2/2PP4/2P3N1/P2B2PP/4RRK1 b - - 7 19",
]

svc = SearchService(
    weights=NnueWeights.random(seed=7), pool_slots=8, batch_capacity=256,
    tt_bytes=8 << 20, backend="jax", pipeline_depth=4, driver_threads=1,
)
svc.set_prefetch(0, adaptive=False)


async def go():
    out = []
    for fen in FENS:
        r = await svc.search(fen, [], nodes=160)
        out.append([
            r.best_move, r.depth,
            [[l.multipv, l.depth, l.is_mate, l.value, list(l.pv)]
             for l in r.lines],
        ])
    return out


analyses = asyncio.run(go())
svc.close()
print(json.dumps({"analyses": analyses, "stats": position_tier.stats()}))
"""


def _run_driver(seg: Path) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = str(_REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env["FISHNET_POSITION_TIER"] = "1"
    env["FISHNET_POSITION_TIER_PATH"] = str(seg)
    env["FISHNET_POSITION_TIER_CAPACITY"] = "4096"
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER],
        env=env, capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_fleet_cache_two_process_smoke(tmp_path):
    """THE cross-process assertion: process A pays the evals and
    populates the shared segment; process B — a genuinely different
    pid — replays the same traffic and must take fleet-scope tier hits
    (owner != pid) with bit-identical analyses."""
    seg = tmp_path / "fleet.seg"
    a = _run_driver(seg)
    b = _run_driver(seg)
    assert b["analyses"] == a["analyses"], (
        "cross-process tier reuse changed analysis output"
    )
    fleet_hits = b["stats"].get("hits.fleet.nnue", 0)
    assert fleet_hits > 0, b["stats"]
    assert a["stats"].get("hits.fleet.nnue", 0) == 0, a["stats"]
    assert a["stats"].get("attach.fleet", 0) == 1
