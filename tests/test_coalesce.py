"""Coalesced multi-group dispatch: bit-exact parity of the segmented
evaluator against per-group dispatch (all three psqt_path rungs, all
wire entry kinds), deterministic width-policy units, and the
``make coalesce-smoke`` contract — a low-occupancy mock workload run
once coalesced and once with FISHNET_NO_COALESCE=1 must produce
identical analyses while the coalesced run issues strictly fewer
device dispatches than eval steps."""

import asyncio
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.jax_eval import (
    evaluate_packed_anchored,
    evaluate_packed_anchored_segmented,
    params_from_weights,
)
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import (
    DispatchProbe,
    SearchService,
    choose_coalesce_width,
    fit_dispatch_cost,
    suggest_pipeline_depth,
)


def _pers_code(aid, is_delta, swap=0):
    """Wire anchor-entry codes (cpp/src/pool.cpp emit_block)."""
    return -(2 + ((aid << 2) | (2 if is_delta else 0) | swap))


def _delta_row(packed, rows, rng):
    """One delta row: adds in [0, DELTA_SLOTS), removals after, each
    region sentinel-padded."""
    packed[rows, :, :2] = rng.integers(0, spec.NUM_FEATURES, (2, 2))
    packed[rows, :, 2:4] = spec.NUM_FEATURES
    packed[rows, :, 4] = spec.DELTA_BASE + rng.integers(
        0, spec.NUM_FEATURES, (2,)
    )
    packed[rows, :, 5:8] = spec.DELTA_BASE + spec.NUM_FEATURES


def _full_rows(packed, rows, rng):
    for r in range(4):
        packed[rows + r] = rng.integers(0, spec.NUM_FEATURES, (2, 8))


def _make_segment(plan, size, tab_rows, rng):
    """One group's packed stream from an entry plan. Plan items:
    ("full",) plain full; ("store", aid) full anchor (re)seed;
    ("pers", aid, swap) persistent anchor delta; ("inbatch", ref, swap)
    in-batch delta vs segment-local entry ref. Entries past the plan
    are padding. Returns the dict the dispatcher would ship."""
    tier = 4 * size + 4
    packed = np.full((tier, 2, 8), spec.NUM_FEATURES, np.uint16)
    parent = np.full((size,), -1, np.int32)
    rows = 0
    for e, item in enumerate(plan):
        kind = item[0]
        if kind in ("full", "store"):
            _full_rows(packed, rows, rng)
            parent[e] = -1 if kind == "full" else _pers_code(item[1], False)
            rows += 4
        elif kind == "pers":
            _delta_row(packed, rows, rng)
            parent[e] = _pers_code(item[1], True, swap=item[2])
            rows += 1
        else:  # in-batch delta
            _delta_row(packed, rows, rng)
            parent[e] = (item[1] << 1) | item[2]
            rows += 1
    packed[rows : rows + 4] = spec.NUM_FEATURES  # the sentinel block
    packed[rows + 4 :] = 60000  # stale garbage: must never be read
    buckets = rng.integers(0, 8, (size,)).astype(np.int32)
    buckets[len(plan) :] = 0
    tab = rng.integers(-3000, 3000, (tab_rows, 2, spec.L1)).astype(np.int32)
    ptab = rng.integers(
        -2000, 2000, (tab_rows, 2, spec.NUM_PSQT_BUCKETS)
    ).astype(np.int32)
    return {
        "n": len(plan), "rows": rows, "packed": packed, "parent": parent,
        "buckets": buckets, "tab": tab, "ptab": ptab,
    }


#: Segments covering every wire entry kind: anchor seeds, persistent
#: deltas (both swaps), in-batch chains off both anchor kinds, plain
#: fulls, and (because n < size) padding entries.
_PLANS = [
    [("store", 0), ("inbatch", 0, 1), ("inbatch", 0, 0), ("full",)],
    [("pers", 2, 1), ("inbatch", 0, 0), ("full",), ("store", 1),
     ("inbatch", 3, 1)],
    [("full",), ("pers", 3, 0), ("inbatch", 1, 1)],
]

#: The fused-interpret rung's plans (size 6, pallas chunk shrunk to 8):
#: the chunk boundary falls at GLOBAL entry 8 = segment 1's local
#: entry 2, an in-batch delta whose anchor (local entry 1, a plain
#: full) sits in the PREVIOUS chunk — the carry-in path is genuinely
#: read, mid-segment. Segment 0 ends with a padding entry.
_INTERPRET_PLANS = [
    [("store", 0), ("inbatch", 0, 1), ("pers", 2, 0), ("inbatch", 2, 1),
     ("full",)],
    [("store", 1), ("full",), ("inbatch", 1, 1), ("inbatch", 1, 0),
     ("pers", 3, 1), ("inbatch", 4, 0)],
]

RUNGS = ["xla", "fused-interpret", "host-material"]


@pytest.mark.parametrize("rung", RUNGS)
def test_segmented_matches_per_group_dispatch(rung, monkeypatch):
    """The tentpole invariant: ONE segmented dispatch over K group
    streams (stacked tables, per-segment row scalars, segment-local
    parent codes) returns, segment by segment, exactly the values and
    updated tables of K separate per-group dispatches — on every
    psqt_path rung.

    The fused-interpret rung runs with a shrunken pallas chunk and
    plans placing a delta right after a mid-segment chunk boundary
    (_INTERPRET_PLANS): the kernel's carry-in must hand each chunk the
    right running anchor across both chunk AND segment boundaries."""
    rng = np.random.default_rng(31)
    params = params_from_weights(NnueWeights.random(seed=5))
    size, tab_rows = 6, 4
    if rung == "fused-interpret":
        from fishnet_tpu.ops import ft_gather

        monkeypatch.setattr(ft_gather, "_CHUNK", 8)
        kw = {"interpret": True}
        plans = _INTERPRET_PLANS
    else:
        kw = {"use_pallas": False}
        plans = _PLANS
    tier = 4 * size + 4
    segs = [_make_segment(p, size, tab_rows, rng) for p in plans]
    for s in segs:
        s["mat"] = (
            rng.integers(-400, 400, (size,)).astype(np.int32)
            if rung == "host-material" else None
        )

    # Per-group references always run the XLA executor: every rung is
    # bit-identical per group (test_ops pins interpret == XLA at the op
    # level), so XLA refs prove the coalesced interpret dispatch
    # against per-group dispatch too — without paying a second
    # interpreter trace for the reference side.
    refs = []
    for s in segs:
        v, nt, npt = evaluate_packed_anchored(
            params, jnp.asarray(s["packed"]), jnp.asarray(s["buckets"]),
            jnp.asarray(s["parent"]),
            None if s["mat"] is None else jnp.asarray(s["mat"]),
            jnp.asarray(s["tab"]),
            jnp.asarray(np.array([s["rows"]], np.int32)),
            jnp.asarray(s["ptab"]), use_pallas=False,
        )
        refs.append((np.asarray(v), np.asarray(nt), np.asarray(npt)))

    packed_cat = np.concatenate([s["packed"][:tier] for s in segs])
    mats = None
    if rung == "host-material":
        mats = jnp.asarray(np.concatenate([s["mat"] for s in segs]))
    got_v, got_t, got_pt = evaluate_packed_anchored_segmented(
        params, jnp.asarray(packed_cat),
        jnp.asarray(np.concatenate([s["buckets"] for s in segs])),
        jnp.asarray(np.concatenate([s["parent"] for s in segs])),
        mats,
        jnp.asarray(np.stack([s["tab"] for s in segs])),
        jnp.asarray(np.array([s["rows"] for s in segs], np.int32)),
        jnp.asarray(np.stack([s["ptab"] for s in segs])), **kw,
    )
    got_v, got_t, got_pt = map(np.asarray, (got_v, got_t, got_pt))
    for k, s in enumerate(segs):
        ref_v, ref_t, ref_pt = refs[k]
        assert np.array_equal(
            got_v[k * size : k * size + s["n"]], ref_v[: s["n"]]
        ), (rung, k)
        assert np.array_equal(got_t[k], ref_t), (rung, k, "anchor tab")
        assert np.array_equal(got_pt[k], ref_pt), (rung, k, "psqt tab")


def test_segment_helper_offsets_and_recode():
    """The device-side segment helpers against hand-built expectations:
    offsets clamp into each segment's own sentinel block and shift by
    its tier; parent codes rebase entry and table bases per segment."""
    from fishnet_tpu.ops.ft_gather import (
        derive_segment_offsets,
        recode_segment_parents,
    )

    # Two segments of 3 entries: [full, inbatch(0), pad] and
    # [store(1), pers(2,swap), pad].
    parent = np.array(
        [[-1, (0 << 1) | 1, -1],
         [_pers_code(1, False), _pers_code(2, True, 1), -1]], np.int32
    )
    seg_rows = np.array([5, 5], np.int32)
    tier = 12
    off = np.asarray(
        derive_segment_offsets(jnp.asarray(parent), jnp.asarray(seg_rows), tier)
    )
    # seg 0: full at 0, delta at 4, padding full clamps to seg_rows=5.
    # seg 1 (base 12): store-full at 12, pers delta at 16, pad at 17.
    assert off.tolist() == [0, 4, 5, 12, 16, 17]

    A = 4
    rec = np.asarray(
        recode_segment_parents(jnp.asarray(parent), A)
    ).reshape(2, 3)
    assert rec[0].tolist() == [-1, (0 << 1) | 1, -1]  # seg 0 unchanged
    # seg 1: table rows shift by A (1 -> 5, 2 -> 6), swap bit kept.
    assert rec[1, 0] == _pers_code(1 + A, False)
    assert rec[1, 1] == _pers_code(2 + A, True, 1)
    assert rec[1, 2] == -1


# -- width policy: probe numbers in -> width out ----------------------------


def test_fit_dispatch_cost_decomposes_bench_transport():
    # BENCH_r05's measured transport tier: rtt_ms_256 ~104,
    # rtt_ms_16384 ~399 -> a ~99 ms fixed term, ~18.7 ms/kslot marginal.
    p = fit_dispatch_cost(0.104, 0.399, 256, 16384)
    assert 90 < p.fixed_ms < 105
    assert 17 < p.marginal_ms_per_kslot < 20
    assert (p.small, p.big) == (256, 16384)


def test_fit_dispatch_cost_clamps_noise():
    # Jitter making the big batch "faster" must not go negative.
    p = fit_dispatch_cost(0.100, 0.080, 256, 16384)
    assert p.marginal_ms_per_kslot == 0.0
    assert p.fixed_ms == 100.0


@pytest.mark.parametrize(
    "fixed,marginal,slots,n_groups,expected",
    [
        # Tunnel probe, low occupancy: fixed dominates -> fuse wide
        # (floored to a power of two).
        (99.0, 18.7, 800, 8, 4),
        (99.0, 18.7, 100, 8, 8),
        # Same probe at full 16k batches: payload dwarfs fixed -> solo.
        (99.0, 18.7, 16384, 8, 1),
        # Mid occupancy: one doubling's worth of fusing.
        (99.0, 18.7, 4096, 8, 2),
        # Local chip (sub-ms fixed cost): never coalesce.
        (0.0, 18.7, 100, 8, 1),
        # Degenerate probe (single-bucket service): assume
        # fixed-dominated, fuse to the group limit.
        (3.0, 0.0, 500, 4, 4),
        # One group: nothing to fuse, whatever the numbers say.
        (99.0, 18.7, 100, 1, 1),
        # The MAX_WIDTH-style cap clamps before the power-of-two floor.
        (1000.0, 0.1, 10, 32, 8),
    ],
)
def test_choose_coalesce_width(fixed, marginal, slots, n_groups, expected):
    assert choose_coalesce_width(fixed, marginal, slots, n_groups) == expected


def test_suggest_pipeline_depth_returns_probe():
    """return_probe=True: the startup probe reports the fixed/marginal
    decomposition alongside the depth, through the same harness."""
    calls = []

    def instant_eval(params, feats, buckets):
        calls.append(len(buckets))
        return np.zeros((len(buckets),), np.int32)

    depth, probe = suggest_pipeline_depth(
        None, size=1024, rounds=3, eval_fn=instant_eval, return_probe=True
    )
    assert depth in (1, 2, 4)
    assert isinstance(probe, DispatchProbe)
    assert probe.small == 64 and probe.big == 1024
    assert probe.fixed_ms >= 0 and probe.marginal_ms_per_kslot >= 0
    assert 64 in calls and 1024 in calls


# -- service wiring ----------------------------------------------------------


def test_no_coalesce_env_disables_layer(monkeypatch):
    monkeypatch.setenv("FISHNET_NO_COALESCE", "1")
    svc = SearchService(
        weights=NnueWeights.random(seed=3), pool_slots=8,
        batch_capacity=128, tt_bytes=4 << 20, backend="jax",
        pipeline_depth=2,
    )
    try:
        assert svc._coalescer is None
        c = svc.counters()
        assert c["dispatches"] == c["eval_steps"]
    finally:
        svc.close()


def test_single_group_service_builds_no_coalescer():
    svc = SearchService(
        weights=NnueWeights.random(seed=3), pool_slots=8,
        batch_capacity=64, tt_bytes=4 << 20, backend="jax",
    )
    try:
        assert svc._coalescer is None
    finally:
        svc.close()


# -- the coalesce-smoke contract (make coalesce-smoke) -----------------------


_SMOKE_FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/4P3/5N2/PPPP1PPP/RNBQKB1R w KQkq - 2 3",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "4rrk1/pp1n3p/3q2pQ/2p1pb2/2PP4/2P3N1/P2B2PP/4RRK1 b - - 7 19",
    "r3r1k1/2p2ppp/p1p1bn2/8/1q2P3/2NPQN2/PPP3PP/R4RK1 b - - 2 15",
    "2rq1rk1/1p3ppp/p2p1n2/2bPp3/4P1b1/2N2N2/PPQ1BPPP/R1B2RK1 w - - 0 12",
    "r1bqk2r/ppp2ppp/2np1n2/2b1p3/2B1P3/2PP1N2/PP3PPP/RNBQK2R w KQkq - 0 6",
    "r2q1rk1/ppp2ppp/2npbn2/2b1p3/4P3/2PP1NN1/PPB2PPP/R1BQ1RK1 w - - 6 9",
]


class _GatedService(SearchService):
    """SearchService whose driver parks after warmup until the gate
    opens — every smoke submission lands in ONE drain pass, making the
    whole schedule (slot assignment, stepping order, TT evolution) a
    deterministic function of the submission sequence. With bit-
    identical eval values, the coalesced and uncoalesced runs then walk
    the exact same search trees."""

    def __init__(self, *args, **kwargs):
        self.gate = threading.Event()
        super().__init__(*args, **kwargs)

    def warmup(self):
        super().warmup()
        self.gate.wait()


def _smoke_run(weights):
    from fishnet_tpu.search import eval_cache

    # Cold-start the process eval cache per run: back-to-back runs of
    # the same FENs would otherwise whole-batch-skip their dispatches
    # (bit-identical output, but the dispatch-count assertions compare
    # coalescer behavior, not cache behavior).
    eval_cache.reset_cache()
    svc = _GatedService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1,
    )
    try:
        # Pin speculation so TT insertions are schedule-deterministic
        # (the cross-backend parity suites' discipline).
        svc.set_prefetch(0, adaptive=False)

        async def go():
            tasks = [
                asyncio.ensure_future(svc.search(fen, [], nodes=280))
                for fen in _SMOKE_FENS
            ]
            await asyncio.sleep(0.3)  # let every submission queue
            svc.gate.set()
            return await asyncio.gather(*tasks)

        results = asyncio.run(go())
        analyses = [
            (
                r.best_move, r.depth, r.nodes,
                tuple(
                    (l.multipv, l.depth, l.is_mate, l.value, tuple(l.pv))
                    for l in r.lines
                ),
            )
            for r in results
        ]
        return analyses, svc.counters()
    finally:
        svc.gate.set()  # never leave the driver parked on a failure
        svc.close()


def test_fused_flush_failure_reaches_every_owner(monkeypatch):
    """A device failure inside a coalesced flush must surface on every
    owning driver exactly like a solo dispatch failure: drivers crash,
    outstanding futures fail, and the service reads dead — the
    supervisor's respawn + degradation ladder sees nothing new."""
    from fishnet_tpu.chess.core import NativeCoreError

    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")
    weights = NnueWeights.random(seed=7)
    svc = _GatedService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1,
    )
    try:
        def boom(*args, **kwargs):
            raise RuntimeError("injected segmented-dispatch failure")

        svc._segmented_fn = boom
        svc._dispatch_eval = boom  # solo flushes die identically

        async def go():
            tasks = [
                asyncio.ensure_future(svc.search(fen, [], nodes=280))
                for fen in _SMOKE_FENS
            ]
            await asyncio.sleep(0.3)
            svc.gate.set()
            return await asyncio.gather(*tasks, return_exceptions=True)

        results = asyncio.run(go())
        assert all(isinstance(r, NativeCoreError) for r in results)
        assert not svc.is_alive()
    finally:
        svc.gate.set()
        svc.close()


def test_coalesce_smoke(monkeypatch):
    """Acceptance: under a low-occupancy mock workload (8 concurrent
    searches spread over 4 pipeline groups, tiny per-step batches) the
    coalesced run issues strictly fewer device dispatches than eval
    steps, with analysis output identical to FISHNET_NO_COALESCE=1."""
    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")  # pin: no timing
    coalesced, c1 = _smoke_run(weights)
    monkeypatch.delenv("FISHNET_COALESCE_WIDTH")
    monkeypatch.setenv("FISHNET_NO_COALESCE", "1")
    plain, c2 = _smoke_run(weights)

    assert coalesced == plain, "coalescing changed analysis output"
    assert c1["eval_steps"] == c2["eval_steps"]
    assert c1["dispatches"] < c1["eval_steps"]
    assert c1["fused_dispatches"] >= 1
    assert c2["dispatches"] == c2["eval_steps"]
    assert c2["fused_dispatches"] == 0

    # The width histogram family is exported (doc/observability.md).
    from fishnet_tpu import telemetry

    text = telemetry.REGISTRY.render_prometheus()
    assert "# TYPE fishnet_dispatch_coalesce_width histogram" in text
