from pathlib import Path

from fishnet_tpu.utils.backoff import RandomizedBackoff
from fishnet_tpu.utils.logger import QueueStatusBar, short_variant_name
from fishnet_tpu.utils.stats import NpsRecorder, StatsRecorder


def test_backoff_bounds_and_growth():
    b = RandomizedBackoff(max_backoff_seconds=30.0)
    first = b.next()
    assert 0.1 <= first <= 0.4
    for _ in range(50):
        d = b.next()
        assert 0.1 <= d <= 30.0
    b.reset()
    assert 0.1 <= b.next() <= 0.4


def test_backoff_cap():
    b = RandomizedBackoff(max_backoff_seconds=0.2)
    for _ in range(20):
        assert b.next() <= 0.2


def test_backoff_full_jitter_distribution_bounds():
    # AWS-style full jitter: attempt k draws uniformly from
    # [0, min(cap, 0.1 * 2**k)) — the low bound is 0 (not 100 ms) and
    # the envelope doubles per attempt until the cap.
    b = RandomizedBackoff(max_backoff_seconds=30.0, jitter="full")
    for attempt in range(24):
        d = b.next()
        assert 0.0 <= d <= min(30.0, 0.1 * 2.0 ** attempt), (attempt, d)
    b.reset()
    # Re-armed: the envelope starts over at 100 ms.
    for _ in range(50):
        assert b.next() <= 0.1
        b.reset()


def test_backoff_full_jitter_spreads_below_decorrelated_floor():
    # The point of full jitter: draws BELOW the decorrelated 100 ms
    # floor are possible (herd spreading). Statistically certain in
    # 200 draws of uniform(0, 0.1].
    b = RandomizedBackoff(max_backoff_seconds=30.0, jitter="full")
    draws = []
    for _ in range(200):
        draws.append(b.next())
        b.reset()
    assert min(draws) < 0.1


def test_backoff_reset_after_grace(monkeypatch):
    import fishnet_tpu.utils.backoff as backoff_mod

    now = [0.0]
    monkeypatch.setattr(backoff_mod.time, "monotonic", lambda: now[0])
    import random as _random

    _random.seed(1234)  # deterministic draws: the outage state is fixed
    b = RandomizedBackoff(max_backoff_seconds=30.0, reset_after=10.0)
    for _ in range(30):  # a long outage: state grows toward the cap
        b.next()
    last = b._last
    assert last > 0.2
    # One success right after the outage must NOT instantly re-arm
    # 100 ms retries: the state only decays one step per reset.
    now[0] += 1.0
    b.reset()
    assert b._last == last / 2.0
    b.reset()  # no new failure since; still inside the grace window
    assert b._last in (last / 4.0, 0.0)  # 0.0 once decayed below the floor
    # Healthy for longer than the grace period: full re-arm.
    now[0] += 11.0
    b.reset()
    assert b._last == 0.0
    assert 0.1 <= b.next() <= 0.4


def test_backoff_rejects_bad_modes():
    import pytest

    with pytest.raises(ValueError):
        RandomizedBackoff(jitter="sawtooth")
    with pytest.raises(ValueError):
        RandomizedBackoff(reset_after=-1.0)


def test_nps_recorder_ewma():
    r = NpsRecorder(cores=2)
    assert r.nps == 800_000
    assert "?" in str(r)
    for _ in range(60):
        r.record(20_000_000)
    assert r.nps > 15_000_000
    assert "?" not in str(r)


def test_stats_persistence(tmp_path: Path):
    path = tmp_path / "stats.json"
    rec = StatsRecorder(cores=1, stats_file=path)
    rec.record_batch(60, 120_000_000, nnue_nps=1_000_000)
    rec2 = StatsRecorder(cores=1, stats_file=path)
    assert rec2.stats.total_batches == 1
    assert rec2.stats.total_positions == 60
    assert rec2.stats.total_nodes == 120_000_000


def test_stats_corrupt_file_resets(tmp_path: Path):
    path = tmp_path / "stats.json"
    path.write_text("{not json")
    rec = StatsRecorder(cores=1, stats_file=path)
    assert rec.stats.total_batches == 0


def test_min_user_backlog_scales_with_speed():
    slow = StatsRecorder(cores=1, no_stats_file=True)
    assert slow.min_user_backlog() > 0  # 400 knps client should self-select out
    fast = StatsRecorder(cores=1, no_stats_file=True)
    for _ in range(100):
        fast.nnue_nps.record(50_000_000)
    assert fast.min_user_backlog() == 0.0


def test_systemd_unit_user_fallback(monkeypatch):
    """User= in the generated system unit: $USER when set, the passwd
    account name when not, and never a literal placeholder (a unit with
    `User=XXX` fails at systemctl start)."""
    import getpass

    from fishnet_tpu import systemd

    monkeypatch.setenv("USER", "alice")
    assert systemd._unit_user() == "alice"

    monkeypatch.delenv("USER", raising=False)
    monkeypatch.setattr(getpass, "getuser", lambda: "realuser")
    assert systemd._unit_user() == "realuser"

    def no_entry():
        raise KeyError("uid has no passwd entry")

    monkeypatch.setattr(getpass, "getuser", no_entry)
    assert systemd._unit_user() == "nobody"


def test_systemd_unit_never_emits_placeholder(monkeypatch):
    import io

    from fishnet_tpu import configure as cfg
    from fishnet_tpu import systemd

    monkeypatch.delenv("USER", raising=False)
    out = io.StringIO()
    systemd.systemd_system(cfg.Opt(command="systemd", no_conf=True), out)
    user_lines = [
        line for line in out.getvalue().splitlines()
        if line.startswith("User=")
    ]
    assert len(user_lines) == 1
    assert user_lines[0] != "User=XXX"
    assert len(user_lines[0]) > len("User=")


def test_systemd_unit_timeout_stop_tracks_drain_deadline():
    """TimeoutStopSec must stay ABOVE the client's drain deadline:
    systemd's SIGTERM (KillMode=mixed) starts the graceful drain, and
    its SIGKILL must only fire after the client's own deadline-abort
    path has had its chance. The unit also reconstructs the
    --drain-deadline flag so the service drains with the same budget
    the operator configured."""
    import io

    from fishnet_tpu import configure as cfg
    from fishnet_tpu import systemd

    out = io.StringIO()
    systemd.systemd_system(
        cfg.Opt(command="systemd", no_conf=True, drain_deadline=40.0), out
    )
    text = out.getvalue()
    assert "TimeoutStopSec=55" in text  # 40s deadline + 15s margin
    assert "--drain-deadline 40s" in text
    assert "KillMode=mixed" in text

    # Default (no flag): the 25 s deadline still gets its margin, and
    # no flag is emitted (the service uses the built-in default).
    out = io.StringIO()
    systemd.systemd_user(cfg.Opt(command="systemd-user", no_conf=True), out)
    text = out.getvalue()
    assert "TimeoutStopSec=40" in text
    assert "--drain-deadline" not in text

    # Fractional deadlines round-trip through parse_duration as ms.
    out = io.StringIO()
    systemd.systemd_system(
        cfg.Opt(command="systemd", no_conf=True, drain_deadline=2.5), out
    )
    assert "--drain-deadline 2500ms" in out.getvalue()


def test_queue_status_bar():
    bar = str(QueueStatusBar(pending=10, cores=4))
    assert bar.startswith("[") and "10" in bar


def test_short_variant_names():
    assert short_variant_name("crazyhouse") == "zh"
    assert short_variant_name("standard") is None
