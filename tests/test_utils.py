from pathlib import Path

from fishnet_tpu.utils.backoff import RandomizedBackoff
from fishnet_tpu.utils.logger import QueueStatusBar, short_variant_name
from fishnet_tpu.utils.stats import NpsRecorder, StatsRecorder


def test_backoff_bounds_and_growth():
    b = RandomizedBackoff(max_backoff_seconds=30.0)
    first = b.next()
    assert 0.1 <= first <= 0.4
    for _ in range(50):
        d = b.next()
        assert 0.1 <= d <= 30.0
    b.reset()
    assert 0.1 <= b.next() <= 0.4


def test_backoff_cap():
    b = RandomizedBackoff(max_backoff_seconds=0.2)
    for _ in range(20):
        assert b.next() <= 0.2


def test_nps_recorder_ewma():
    r = NpsRecorder(cores=2)
    assert r.nps == 800_000
    assert "?" in str(r)
    for _ in range(60):
        r.record(20_000_000)
    assert r.nps > 15_000_000
    assert "?" not in str(r)


def test_stats_persistence(tmp_path: Path):
    path = tmp_path / "stats.json"
    rec = StatsRecorder(cores=1, stats_file=path)
    rec.record_batch(60, 120_000_000, nnue_nps=1_000_000)
    rec2 = StatsRecorder(cores=1, stats_file=path)
    assert rec2.stats.total_batches == 1
    assert rec2.stats.total_positions == 60
    assert rec2.stats.total_nodes == 120_000_000


def test_stats_corrupt_file_resets(tmp_path: Path):
    path = tmp_path / "stats.json"
    path.write_text("{not json")
    rec = StatsRecorder(cores=1, stats_file=path)
    assert rec.stats.total_batches == 0


def test_min_user_backlog_scales_with_speed():
    slow = StatsRecorder(cores=1, no_stats_file=True)
    assert slow.min_user_backlog() > 0  # 400 knps client should self-select out
    fast = StatsRecorder(cores=1, no_stats_file=True)
    for _ in range(100):
        fast.nnue_nps.record(50_000_000)
    assert fast.min_user_backlog() == 0.0


def test_systemd_unit_user_fallback(monkeypatch):
    """User= in the generated system unit: $USER when set, the passwd
    account name when not, and never a literal placeholder (a unit with
    `User=XXX` fails at systemctl start)."""
    import getpass

    from fishnet_tpu import systemd

    monkeypatch.setenv("USER", "alice")
    assert systemd._unit_user() == "alice"

    monkeypatch.delenv("USER", raising=False)
    monkeypatch.setattr(getpass, "getuser", lambda: "realuser")
    assert systemd._unit_user() == "realuser"

    def no_entry():
        raise KeyError("uid has no passwd entry")

    monkeypatch.setattr(getpass, "getuser", no_entry)
    assert systemd._unit_user() == "nobody"


def test_systemd_unit_never_emits_placeholder(monkeypatch):
    import io

    from fishnet_tpu import configure as cfg
    from fishnet_tpu import systemd

    monkeypatch.delenv("USER", raising=False)
    out = io.StringIO()
    systemd.systemd_system(cfg.Opt(command="systemd", no_conf=True), out)
    user_lines = [
        line for line in out.getvalue().splitlines()
        if line.startswith("User=")
    ]
    assert len(user_lines) == 1
    assert user_lines[0] != "User=XXX"
    assert len(user_lines[0]) > len("User=")


def test_queue_status_bar():
    bar = str(QueueStatusBar(pending=10, cores=4))
    assert bar.startswith("[") and "10" in bar


def test_short_variant_names():
    assert short_variant_name("crazyhouse") == "zh"
    assert short_variant_name("standard") is None
