"""Pallas kernel parity tests (interpreter mode on CPU; the same kernel
is exercised on real TPU hardware by bench/verify runs)."""

import numpy as np
import pytest

import jax.numpy as jnp

from fishnet_tpu.ops.ft_gather import _xla_ft_accumulate, ft_accumulate


def _fixture(n_features=512, l1=1024, batch=5, active=32, seed=0):
    rng = np.random.default_rng(seed)
    ft_w = jnp.asarray(
        np.vstack(
            [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
        ).astype(np.int16)
    )
    ft_b = jnp.asarray(rng.integers(-100, 100, (l1,)).astype(np.int16))
    idx = rng.integers(0, n_features, (batch, 2, active)).astype(np.int32)
    # Pad a few slots with the sentinel row like real feature extraction.
    idx[:, :, active - 3 :] = n_features
    return ft_w, ft_b, jnp.asarray(idx)


def test_pallas_ft_gather_matches_xla_interpret():
    ft_w, ft_b, idx = _fixture()
    ref = np.asarray(_xla_ft_accumulate(ft_w, ft_b, idx))
    got = np.asarray(ft_accumulate(ft_w, ft_b, idx, interpret=True))
    assert np.array_equal(ref, got)


def test_pallas_ft_gather_sentinel_rows_are_noops():
    ft_w, ft_b, _ = _fixture()
    n = ft_w.shape[0] - 1
    idx = jnp.full((3, 2, 32), n, dtype=jnp.int32)  # all padding
    got = np.asarray(ft_accumulate(ft_w, ft_b, idx, interpret=True))
    expected = np.broadcast_to(np.asarray(ft_b, np.int32), got.shape)
    assert np.array_equal(got, expected)


def test_auto_selection_falls_back_on_cpu():
    # On the CPU test backend the auto path must use XLA (and agree).
    ft_w, ft_b, idx = _fixture(batch=2)
    auto = np.asarray(ft_accumulate(ft_w, ft_b, idx))
    ref = np.asarray(_xla_ft_accumulate(ft_w, ft_b, idx))
    assert np.array_equal(auto, ref)


def test_evaluate_batch_still_matches_cpp_oracle_path():
    # evaluate_batch routes through ft_accumulate now; the existing nnue
    # parity suite (test_nnue.py) covers full-score parity — here just a
    # smoke check that the plumbing holds shapes.
    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.jax_eval import evaluate_batch, params_from_weights
    from fishnet_tpu.nnue.weights import NnueWeights

    params = params_from_weights(NnueWeights.random(seed=1))
    rng = np.random.default_rng(2)
    idx = rng.integers(
        0, spec.NUM_FEATURES + 1, (4, 2, spec.MAX_ACTIVE_FEATURES)
    ).astype(np.int32)
    buckets = rng.integers(0, spec.NUM_PSQT_BUCKETS, (4,)).astype(np.int32)
    out = np.asarray(evaluate_batch(params, jnp.asarray(idx), jnp.asarray(buckets)))
    assert out.shape == (4,)
    assert np.all(np.abs(out) < 10_000_000)


@pytest.mark.parametrize("batch", [1, 3, 300])
def test_pallas_chunking_boundaries(batch):
    # _CHUNK = 256: cover under, at-boundary-crossing, and tiny batches.
    ft_w, ft_b, idx = _fixture(batch=batch, l1=1024)
    ref = np.asarray(_xla_ft_accumulate(ft_w, ft_b, idx))
    got = np.asarray(ft_accumulate(ft_w, ft_b, idx, interpret=True))
    assert np.array_equal(ref, got)


def _block_batch(n_features, active, n_blocks, block, rng):
    """Anchor-protocol batch: each block is one full entry followed by
    delta children referencing it (the most recent preceding full
    entry), with random perspective swaps — the shape the native pool
    emits (cpp/src/pool.cpp evaluate_block)."""
    from fishnet_tpu.ops.ft_gather import _DELTA_SLOTS

    delta_base = n_features + 1
    batch = n_blocks * block
    idx = np.full((batch, 2, active), n_features, np.int32)
    parent = np.full((batch,), -1, np.int32)
    for s in range(0, batch, block):
        idx[s, :, : active - 3] = rng.integers(0, n_features, (2, active - 3))
        for j in range(1, block):
            e = s + j
            swap = int(rng.integers(0, 2))
            parent[e] = (s << 1) | swap
            for p in range(2):
                n_add = int(rng.integers(0, _DELTA_SLOTS + 1))
                n_rem = int(rng.integers(0, _DELTA_SLOTS + 1))
                idx[e, p, :n_add] = rng.integers(0, n_features, n_add)
                idx[e, p, _DELTA_SLOTS : _DELTA_SLOTS + n_rem] = (
                    delta_base + rng.integers(0, n_features, n_rem)
                )
                idx[e, p, _DELTA_SLOTS + n_rem : 2 * _DELTA_SLOTS] = (
                    delta_base + n_features
                )
    return jnp.asarray(idx), jnp.asarray(parent), delta_base


def test_pallas_anchored_resolution_interpret(monkeypatch):
    """Anchored (in-VMEM running anchor) delta resolution must agree
    bit-exactly with the XLA explicit-index fallback, including across
    pallas-call chunk boundaries (the carry-in path): shrink _CHUNK so
    blocks straddle chunks and children must resolve against an anchor
    computed by the PREVIOUS pallas call."""
    from fishnet_tpu.ops import ft_gather

    monkeypatch.setattr(ft_gather, "_CHUNK", 8)
    n_features, l1, active = 512, 1024, 32
    rng = np.random.default_rng(11)
    ft_w = jnp.asarray(
        np.vstack(
            [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
        ).astype(np.int16)
    )
    ft_b = jnp.asarray(rng.integers(-100, 100, (l1,)).astype(np.int16))
    # Blocks of 5 against chunks of 8: entries 8-9 (etc.) are deltas
    # whose anchor lives in the previous chunk.
    idx, parent, delta_base = _block_batch(n_features, active, 4, 5, rng)
    ref = np.asarray(
        ft_gather.ft_accumulate(
            ft_w, ft_b, idx, use_pallas=False,
            delta_base=delta_base, parent=parent,
        )
    )
    got = np.asarray(
        ft_gather.ft_accumulate(
            ft_w, ft_b, idx, interpret=True,
            delta_base=delta_base, parent=parent,
        )
    )
    assert np.array_equal(ref, got)


def test_pallas_sparse_delta_mode_interpret():
    """The kernel's SPARSE mode (mode-predicated transfers, removal-slot
    index decode, adds-minus-removes reduce) must agree with the XLA
    signed fallback in interpreter mode — the only way to execute this
    branch offline before it serves real TPU traffic."""
    from fishnet_tpu.ops.ft_gather import _DELTA_SLOTS

    n_features, l1, active = 512, 1024, 32
    delta_base = n_features + 1
    rng = np.random.default_rng(3)
    ft_w = jnp.asarray(
        np.vstack(
            [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
        ).astype(np.int16)
    )
    ft_b = jnp.asarray(rng.integers(-100, 100, (l1,)).astype(np.int16))

    batch = 8
    idx = np.full((batch, 2, active), n_features, np.int32)
    sparse = np.zeros((batch,), bool)
    for b in range(batch):
        if b % 2 == 0:  # dense entry
            idx[b, :, : active - 3] = rng.integers(
                0, n_features, (2, active - 3)
            )
        else:  # sparse delta entry: adds + encoded removals, region-padded
            sparse[b] = True
            for p in range(2):
                n_add = int(rng.integers(0, _DELTA_SLOTS + 1))
                n_rem = int(rng.integers(0, _DELTA_SLOTS + 1))
                idx[b, p, :n_add] = rng.integers(0, n_features, n_add)
                idx[b, p, _DELTA_SLOTS : _DELTA_SLOTS + n_rem] = (
                    delta_base + rng.integers(0, n_features, n_rem)
                )
                idx[b, p, _DELTA_SLOTS + n_rem : 2 * _DELTA_SLOTS] = (
                    delta_base + n_features
                )

    ref = np.asarray(
        _xla_ft_accumulate(ft_w, ft_b, jnp.asarray(idx), delta_base=delta_base)
    )
    got = np.asarray(
        ft_accumulate(
            ft_w, ft_b, jnp.asarray(idx),
            interpret=True, delta_base=delta_base,
            sparse=jnp.asarray(sparse),
        )
    )
    assert np.array_equal(ref, got)


def _pers_code(aid, is_delta, swap=0):
    """Wire anchor-entry codes (cpp/src/pool.cpp emit_block)."""
    return -(2 + ((aid << 2) | (2 if is_delta else 0) | swap))


def _anchored_fixture(seed=21):
    n_features, l1, active = 512, 1024, 32
    rng = np.random.default_rng(seed)
    ft_w = np.vstack(
        [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
    ).astype(np.int16)
    ft_b = rng.integers(-100, 100, (l1,)).astype(np.int16)
    return n_features, l1, active, rng, ft_w, ft_b


def test_persistent_anchor_resolution_matches_manual():
    """Persistent parent codes resolve against the anchor TABLE (with
    the perspective swap), and a resolved persistent entry anchors the
    in-batch deltas that follow it — checked against hand-built sums in
    both the XLA fallback and the fused kernel (interpreter mode)."""
    from fishnet_tpu.ops.ft_gather import _DELTA_SLOTS, ft_accumulate

    n_features, l1, active, rng, ft_w, ft_b = _anchored_fixture()
    delta_base = n_features + 1
    tab = rng.integers(-5000, 5000, (4, 2, l1)).astype(np.int32)

    # e0: full storing row 1; e1: persistent delta vs row 2 (swapped),
    # stores row 2; e2: in-batch delta vs e1; e3: plain full.
    idx = np.full((4, 2, active), n_features, np.int32)
    feats0 = [[1, 5, 9], [2, 6]]
    adds1, rems1 = [[7], [8, 11]], [[3], []]
    adds2, rems2 = [[20], []], [[7], [8]]
    feats3 = [[100, 200], [300]]
    for p in range(2):
        idx[0, p, : len(feats0[p])] = feats0[p]
        idx[1, p, : len(adds1[p])] = adds1[p]
        idx[1, p, _DELTA_SLOTS : _DELTA_SLOTS + len(rems1[p])] = [
            delta_base + f for f in rems1[p]
        ]
        idx[1, p, _DELTA_SLOTS + len(rems1[p]) : 2 * _DELTA_SLOTS] = (
            delta_base + n_features
        )
        idx[2, p, : len(adds2[p])] = adds2[p]
        idx[2, p, _DELTA_SLOTS : _DELTA_SLOTS + len(rems2[p])] = [
            delta_base + f for f in rems2[p]
        ]
        idx[2, p, _DELTA_SLOTS + len(rems2[p]) : 2 * _DELTA_SLOTS] = (
            delta_base + n_features
        )
        idx[3, p, : len(feats3[p])] = feats3[p]
    parent = np.array(
        [_pers_code(1, False), _pers_code(2, True, swap=1), (1 << 1), -1],
        np.int32,
    )

    w64, b64 = ft_w.astype(np.int64), ft_b.astype(np.int64)
    exp = np.zeros((4, 2, l1), np.int64)
    for p in range(2):
        exp[0, p] = b64 + w64[feats0[p]].sum(0)
        exp[1, p] = tab[2, 1 - p] + w64[adds1[p]].sum(0) - w64[rems1[p]].sum(0)
        exp[2, p] = exp[1, p] + w64[adds2[p]].sum(0) - w64[rems2[p]].sum(0)
        exp[3, p] = b64 + w64[feats3[p]].sum(0)

    for interpret in (False, True):
        got = np.asarray(
            ft_accumulate(
                jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
                use_pallas=False, interpret=interpret,
                delta_base=delta_base, parent=jnp.asarray(parent),
                anchor_tab=jnp.asarray(tab),
            )
        )
        assert np.array_equal(got.astype(np.int64), exp), interpret


def test_persistent_anchor_across_chunks_interpret(monkeypatch):
    """Persistent entries DMA their table rows regardless of chunk
    position, and the carry rule treats persistent-resolved entries as
    anchors: shrink _CHUNK so persistent entries and their in-batch
    children straddle pallas calls, then compare against the XLA
    fallback."""
    from fishnet_tpu.ops import ft_gather

    monkeypatch.setattr(ft_gather, "_CHUNK", 4)
    n_features, l1, active, rng, ft_w, ft_b = _anchored_fixture(seed=22)
    delta_base = n_features + 1
    idx, parent, _ = _block_batch(n_features, active, 5, 3, rng)
    idx, parent = np.asarray(idx).copy(), np.asarray(parent).copy()
    # Rewrite every block head to an anchor-entry code: alternate
    # full-stores and persistent deltas (vs distinct table rows).
    tab = rng.integers(-5000, 5000, (8, 2, l1)).astype(np.int32)
    for k, s in enumerate(range(0, len(parent), 3)):
        if k % 2 == 0:
            parent[s] = _pers_code(k, False)
        else:
            parent[s] = _pers_code(k, True, swap=int(rng.integers(0, 2)))
            row = np.full((2, active), n_features, np.int32)
            for p in range(2):
                row[p, :2] = rng.integers(0, n_features, 2)
                row[p, 4:6] = delta_base + rng.integers(0, n_features, 2)
                row[p, 6:8] = delta_base + n_features
            idx[s] = row
    ref = np.asarray(
        ft_gather.ft_accumulate(
            jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
            use_pallas=False, delta_base=delta_base,
            parent=jnp.asarray(parent), anchor_tab=jnp.asarray(tab),
        )
    )
    got = np.asarray(
        ft_gather.ft_accumulate(
            jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
            interpret=True, delta_base=delta_base,
            parent=jnp.asarray(parent), anchor_tab=jnp.asarray(tab),
        )
    )
    assert np.array_equal(ref, got)


def test_evaluate_packed_anchored_offsets_and_store():
    """The anchored packed path derives row offsets by cumsum (4 per
    full, 1 per delta; padding clamps into the tier-end sentinel
    block), returns values identical to the explicit-offsets packed
    path, and scatters anchor entries' resolved accumulators into
    their table rows — the PSQT table included (ABI 9 device-PSQT
    wire: material=None)."""
    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.jax_eval import (
        evaluate_packed,
        evaluate_packed_anchored,
        params_from_weights,
    )
    from fishnet_tpu.nnue.weights import NnueWeights

    params = params_from_weights(NnueWeights.random(seed=5))
    rng = np.random.default_rng(6)
    B, A = 6, 4
    real = 4  # entries; the last two are padding
    tier = 4 * B + 4
    packed = np.full((tier, 2, 8), spec.NUM_FEATURES, np.uint16)
    parent = np.full((B,), -1, np.int32)
    offsets = np.zeros((B,), np.int32)
    rows = 0
    # e0 full-store(row 0); e1 in-batch delta vs e0; e2 persistent delta
    # vs row 3; e3 plain full; e4/e5 padding.
    specs = [("full_store", 0), ("inbatch", 0), ("pers", 3), ("full", 0)]
    for e, (kind, aid) in enumerate(specs):
        offsets[e] = rows
        if kind in ("full_store", "full"):
            for r in range(4):
                packed[rows + r] = rng.integers(0, spec.NUM_FEATURES, (2, 8))
            parent[e] = _pers_code(aid, False) if kind == "full_store" else -1
            rows += 4
        else:
            packed[rows, :, :2] = rng.integers(0, spec.NUM_FEATURES, (2, 2))
            packed[rows, :, 2:4] = spec.NUM_FEATURES
            packed[rows, :, 4] = spec.DELTA_BASE + rng.integers(
                0, spec.NUM_FEATURES, (2,)
            )
            packed[rows, :, 5:8] = spec.DELTA_BASE + spec.NUM_FEATURES
            parent[e] = (0 << 1) if kind == "inbatch" else _pers_code(
                aid, True
            )
            rows += 1
    offsets[real:] = rows
    # ONE sentinel block at the emitted-stream end; the rows between it
    # and the tier end stay deliberately garbage (stale in production)
    # to prove padding offsets clamp to n_rows and never read them.
    packed[rows : rows + 4] = spec.NUM_FEATURES
    packed[rows + 4 :] = 60000  # would be far out of table bounds
    buckets = rng.integers(0, 8, (B,)).astype(np.int32)
    material = rng.integers(-400, 400, (B,)).astype(np.int32)
    tab = rng.integers(-3000, 3000, (A, 2, spec.L1)).astype(np.int32)
    ptab = rng.integers(-2000, 2000, (A, 2, spec.NUM_PSQT_BUCKETS)).astype(
        np.int32
    )

    vals, new_tab, new_ptab = evaluate_packed_anchored(
        params, jnp.asarray(packed), jnp.asarray(buckets),
        jnp.asarray(parent), jnp.asarray(material), jnp.asarray(tab),
        jnp.asarray(np.array([rows], np.int32)), jnp.asarray(ptab),
    )
    vals, new_tab = np.asarray(vals), np.asarray(new_tab)
    # Host-material mode: the PSQT table rides through untouched.
    assert np.array_equal(np.asarray(new_ptab), ptab)

    # Table-independent entries check against the explicit-offsets
    # packed path (persistent codes stripped to their wire-equivalent
    # plain forms).
    pure = [0, 1, 3]
    # All anchor codes map to plain fulls: entry 0's store-full IS a
    # full, and the persistent entry (2, excluded from `pure`) merely
    # decodes unused rows under its explicit offset.
    ref = np.asarray(
        evaluate_packed(
            params, jnp.asarray(packed), jnp.asarray(offsets),
            jnp.asarray(buckets),
            jnp.asarray(np.where(parent <= -2, -1, parent)),
            jnp.asarray(material),
        )
    )
    assert np.array_equal(vals[pure], ref[pure])
    # The persistent entry (2) checks against the ft-level resolution
    # (independently verified above) fed through the head directly —
    # covering the integrated path's offsets derivation and expansion.
    from fishnet_tpu.nnue.jax_eval import _evaluate_from_acc, expand_packed
    from fishnet_tpu.ops.ft_gather import ft_accumulate

    dense = expand_packed(
        jnp.asarray(packed), jnp.asarray(offsets), jnp.asarray(parent)
    )
    acc = ft_accumulate(
        params["ft_w"], params["ft_b"], dense, use_pallas=False,
        delta_base=spec.DELTA_BASE, parent=jnp.asarray(parent),
        anchor_tab=jnp.asarray(tab),
    )
    head = np.asarray(
        _evaluate_from_acc(
            params, acc, dense, jnp.asarray(buckets), jnp.asarray(parent),
            jnp.asarray(material),
        )
    )
    assert vals[2] == head[2]

    # Store semantics: rows 0 (full-store) and 3 (persistent) updated,
    # rows 1-2 untouched.
    assert not np.array_equal(new_tab[0], tab[0])
    assert not np.array_equal(new_tab[3], tab[3])
    assert np.array_equal(new_tab[1], tab[1])
    assert np.array_equal(new_tab[2], tab[2])

    # DEVICE-PSQT wire (material=None): the fused pass resolves PSQT
    # against ptab, the head selects the bucket itself, and anchor
    # entries' resolved PSQT accumulators scatter into their rows.
    vals_d, _, new_ptab_d = evaluate_packed_anchored(
        params, jnp.asarray(packed), jnp.asarray(buckets),
        jnp.asarray(parent), None, jnp.asarray(tab),
        jnp.asarray(np.array([rows], np.int32)), jnp.asarray(ptab),
    )
    vals_d, new_ptab_d = np.asarray(vals_d), np.asarray(new_ptab_d)
    psqt = np.asarray(
        ft_accumulate(
            params["ft_w"], params["ft_b"], dense, use_pallas=False,
            delta_base=spec.DELTA_BASE, parent=jnp.asarray(parent),
            anchor_tab=jnp.asarray(tab), ft_psqt=params["ft_psqt"],
            psqt_tab=jnp.asarray(ptab),
        )[1]
    )
    sel = psqt[np.arange(B), :, buckets]
    d = sel[:, 0].astype(np.int64) - sel[:, 1]
    mat = np.where(d >= 0, d // 2, -((-d) // 2))  # C truncation
    ref_d = np.asarray(
        _evaluate_from_acc(
            params, acc, dense, jnp.asarray(buckets), jnp.asarray(parent),
            jnp.asarray(mat.astype(np.int32)),
        )
    )
    assert np.array_equal(vals_d[:real], ref_d[:real])
    assert not np.array_equal(new_ptab_d[0], ptab[0])
    assert not np.array_equal(new_ptab_d[3], ptab[3])
    assert np.array_equal(new_ptab_d[1], ptab[1])
    assert np.array_equal(new_ptab_d[2], ptab[2])
    # The stored PSQT rows ARE the resolved accumulators.
    assert np.array_equal(new_ptab_d[0], psqt[0])
    assert np.array_equal(new_ptab_d[3], psqt[2])


def build_psqt_parity_batch(n_features, active, rng, n_blocks=6, block=4,
                            n_tab=8):
    """Batch covering EVERY wire entry kind the PSQT path must resolve:
    plain fulls (-1), anchor full (re)seeds, persistent anchor deltas
    (with swap), in-batch deltas (with swap), removal encodings
    (DELTA_BASE + f), and the per-region sentinel padding. In-batch refs
    always point at the most recent preceding anchor entry (the pool's
    emit contract, which the kernel's running anchor depends on)."""
    from fishnet_tpu.ops.ft_gather import _DELTA_SLOTS

    delta_base = n_features + 1
    batch = n_blocks * block
    idx = np.full((batch, 2, active), n_features, np.int32)
    parent = np.full((batch,), -1, np.int32)

    def fill_full(e):
        idx[e, :, : active - 3] = rng.integers(0, n_features, (2, active - 3))

    def fill_delta(e):
        idx[e] = n_features
        for p in range(2):
            n_add = int(rng.integers(0, _DELTA_SLOTS + 1))
            n_rem = int(rng.integers(0, _DELTA_SLOTS + 1))
            idx[e, p, :n_add] = rng.integers(0, n_features, n_add)
            idx[e, p, _DELTA_SLOTS : _DELTA_SLOTS + n_rem] = (
                delta_base + rng.integers(0, n_features, n_rem)
            )
            idx[e, p, _DELTA_SLOTS + n_rem : 2 * _DELTA_SLOTS] = (
                delta_base + n_features
            )

    for k, s in enumerate(range(0, batch, block)):
        kind = k % 3
        if kind == 0 and k > 0:  # plain full (entry 0 stays an anchor)
            fill_full(s)
        elif kind == 2 and k > 0:  # persistent anchor delta (load+store)
            parent[s] = _pers_code(k % n_tab, True, swap=int(rng.integers(0, 2)))
            fill_delta(s)
        else:  # anchor full (re)seed
            parent[s] = _pers_code(k % n_tab, False)
            fill_full(s)
        for j in range(1, block):
            e = s + j
            parent[e] = (s << 1) | int(rng.integers(0, 2))
            fill_delta(e)
    return idx, parent, delta_base


def np_resolve_psqt(idx, parent, psqt_rows, ptab, delta_base):
    """Independent numpy reconstruction of the resolved PSQT accumulator
    stream — the same walk cpp/src/pool.cpp fill_full/fill_delta does
    host-side (explicit chains, no kernel machinery). int64 to prove no
    intermediate overflow hides in the int32 paths."""
    B = idx.shape[0]
    nb = psqt_rows.shape[1]
    rows64 = psqt_rows.astype(np.int64)
    out = np.zeros((B, 2, nb), np.int64)
    for b in range(B):
        code = int(parent[b])
        v = -code - 2
        is_delta = code >= 0 or (code <= -2 and (v & 2) != 0)
        if code >= 0:
            base, swap = out[int(code) >> 1].copy(), code & 1
        elif code <= -2 and (v & 2) != 0:
            base, swap = ptab[v >> 2].astype(np.int64).copy(), v & 1
        else:
            base, swap = np.zeros((2, nb), np.int64), 0
        if swap:
            base = base[::-1]
        acc = base if is_delta else np.zeros((2, nb), np.int64)
        for p in range(2):
            for f in idx[b, p]:
                f = int(f)
                if f >= delta_base:
                    acc[p] -= rows64[f - delta_base]
                else:
                    acc[p] += rows64[f]
        out[b] = acc
    return out


def host_material_np(psqt, buckets):
    """The pool's host-side material term from a resolved [B, 2, 8] PSQT
    accumulator: bucket select, (stm - opp) / 2 with C truncation."""
    sel = psqt[np.arange(len(buckets)), :, buckets].astype(np.int64)
    d = sel[:, 0] - sel[:, 1]
    return np.where(d >= 0, d // 2, -((-d) // 2)).astype(np.int32)


def test_fused_psqt_parity_all_entry_kinds(monkeypatch):
    """Satellite parity pin: the fused kernel's PSQT accumulator is
    bit-identical to the XLA path, to an independent numpy chain walk
    (the host material recomputation), and both material routes produce
    identical SCORES — across plain fulls, in-batch deltas with swap,
    removal encodings, and persistent anchor store/load codes, with
    chunk boundaries straddled (_CHUNK shrunk so carries engage)."""
    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.jax_eval import (
        _evaluate_from_acc,
        params_from_weights,
    )
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.ops import ft_gather

    # _CHUNK=6 against blocks of 4: the 4..7 block's children straddle
    # the first chunk boundary (carry-in engages) and the 12..15 block's
    # persistent head lands exactly ON a boundary.
    # active=16 halves the kernel's unrolled transfer trace (the test's
    # cost is trace-bound); the full-spec oracle test below keeps the
    # 32-slot shape covered.
    monkeypatch.setattr(ft_gather, "_CHUNK", 6)
    n_features, l1, active = 512, 1024, 16
    rng = np.random.default_rng(77)
    ft_w = np.vstack(
        [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
    ).astype(np.int16)
    ft_b = rng.integers(-100, 100, (l1,)).astype(np.int16)
    psqt_rows = np.vstack(
        [rng.integers(-3000, 3000, (n_features, 8)), np.zeros((1, 8))]
    ).astype(np.int32)
    idx, parent, delta_base = build_psqt_parity_batch(
        n_features, active, rng, n_blocks=4, block=4
    )
    B = len(parent)
    tab = rng.integers(-5000, 5000, (8, 2, l1)).astype(np.int32)
    ptab = rng.integers(-4000, 4000, (8, 2, 8)).astype(np.int32)

    args = dict(delta_base=delta_base, parent=jnp.asarray(parent),
                anchor_tab=jnp.asarray(tab), ft_psqt=jnp.asarray(psqt_rows),
                psqt_tab=jnp.asarray(ptab))
    acc_x, psqt_x = ft_gather.ft_accumulate(
        jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
        use_pallas=False, **args,
    )
    acc_f, psqt_f = ft_gather.ft_accumulate(
        jnp.asarray(ft_w), jnp.asarray(ft_b), jnp.asarray(idx),
        interpret=True, **args,
    )
    acc_x, psqt_x = np.asarray(acc_x), np.asarray(psqt_x)
    acc_f, psqt_f = np.asarray(acc_f), np.asarray(psqt_f)
    # Fused == XLA, accumulators and PSQT alike, bit for bit.
    assert np.array_equal(acc_x, acc_f)
    assert np.array_equal(psqt_x, psqt_f)
    # == the independent host chain walk (no int32 overflow hid either).
    ref = np_resolve_psqt(idx, parent, psqt_rows, ptab, delta_base)
    assert np.array_equal(psqt_x.astype(np.int64), ref)

    # Host-material wire vs device-PSQT wire: identical SCORES.
    params = params_from_weights(NnueWeights.random(seed=5))
    buckets = rng.integers(0, spec.NUM_PSQT_BUCKETS, (B,)).astype(np.int32)
    material = host_material_np(psqt_x, buckets)
    via_host = np.asarray(_evaluate_from_acc(
        params, jnp.asarray(acc_x), jnp.asarray(idx), jnp.asarray(buckets),
        jnp.asarray(parent), jnp.asarray(material),
    ))
    via_device = np.asarray(_evaluate_from_acc(
        params, jnp.asarray(acc_f), jnp.asarray(idx), jnp.asarray(buckets),
        jnp.asarray(parent), None, psqt=jnp.asarray(psqt_f),
    ))
    assert np.array_equal(via_host, via_device)


def test_device_psqt_score_parity_with_cpp_oracle(tmp_path):
    """Full-spec four-way parity on REAL positions: the C++ scalar
    oracle, the host-material wire, the XLA device-PSQT path, and the
    fused kernel (interpreter mode) agree bit for bit on the final
    centipawn scores."""
    import random

    from fishnet_tpu.chess import Board
    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.cpp_oracle import CppNnue
    from fishnet_tpu.nnue.jax_eval import (
        _evaluate_from_acc,
        evaluate_batch,
        params_from_weights,
    )
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.ops.ft_gather import ft_accumulate

    weights = NnueWeights.random(seed=7)
    net = tmp_path / "parity.nnue"
    weights.save(net)
    oracle = CppNnue(net)

    random.seed(99)
    boards = []
    while len(boards) < 12:
        b = Board()
        for _ in range(random.randrange(4, 70)):
            if b.outcome() != 0:
                break
            b.push_uci(random.choice(b.legal_moves()))
        boards.append(b)

    idx = np.stack([b.nnue_features()[0] for b in boards]).astype(np.int32)
    buckets = np.array(
        [b.nnue_features()[1] for b in boards], dtype=np.int32
    )
    params = params_from_weights(weights)

    cpp = np.array([oracle.evaluate(b) for b in boards], dtype=np.int32)

    # Host material, recomputed the way cpp fill_full walks ft_psqt.
    psqt_acc = np.zeros((len(boards), 2, spec.NUM_PSQT_BUCKETS), np.int64)
    for i in range(len(boards)):
        for p in range(2):
            for f in idx[i, p]:
                if f < spec.NUM_FEATURES:
                    psqt_acc[i, p] += weights.ft_psqt[f]
    material = host_material_np(psqt_acc, buckets)
    via_host = np.asarray(evaluate_batch(
        params, jnp.asarray(idx), jnp.asarray(buckets),
        material=jnp.asarray(material),
    ))
    # Device PSQT, XLA path (material=None routes through the same
    # fused-pass code with the XLA executor on CPU).
    via_xla = np.asarray(
        evaluate_batch(params, jnp.asarray(idx), jnp.asarray(buckets))
    )
    # Device PSQT, fused kernel in interpreter mode.
    acc, psqt = ft_accumulate(
        params["ft_w"], params["ft_b"], jnp.asarray(idx),
        interpret=True, ft_psqt=params["ft_psqt"],
    )
    via_fused = np.asarray(_evaluate_from_acc(
        params, acc, jnp.asarray(idx), jnp.asarray(buckets), None, None,
        psqt=psqt,
    ))
    assert np.array_equal(cpp, via_host)
    assert np.array_equal(cpp, via_xla)
    assert np.array_equal(cpp, via_fused)


def test_decode_parent_masks_swap_for_plain_fulls():
    """Plain fulls (-1) decode v=-1 whose low bit is set; the decoded
    swap must be masked with (in_batch | stores) so fulls come back
    swap=0 — any future consumer of the decoded mask relies on it."""
    from fishnet_tpu.ops.ft_gather import decode_parent

    parent = jnp.asarray(
        np.array(
            [
                -1,  # plain full
                5,  # in-batch delta ref 2, swap=1
                4,  # in-batch delta ref 2, swap=0
                -(2 + (3 << 2) + 2 + 1),  # persistent, row 3, swap=1
                -(2 + (7 << 2)),  # full anchor reseed row 7, swap=0
            ],
            np.int32,
        )
    )
    in_batch, persistent, stores, ref, swap, aid = decode_parent(parent)
    assert np.asarray(swap).tolist() == [False, True, False, True, False]
    assert np.asarray(in_batch).tolist() == [False, True, True, False, False]
    assert np.asarray(persistent).tolist() == [False, False, False, True, False]
    assert np.asarray(aid).tolist() == [0, 0, 0, 3, 7]


def test_persistent_codes_without_table_raise_eagerly():
    from fishnet_tpu.nnue import spec as _spec

    ft_w, ft_b, idx = _fixture(batch=3)
    parent = np.array([-1, -4, -1], np.int32)  # -4: persistent delta code
    with pytest.raises(ValueError, match="anchor_tab"):
        ft_accumulate(
            ft_w, ft_b, idx, use_pallas=False,
            delta_base=_spec.DELTA_BASE, parent=jnp.asarray(parent),
        )


def test_persistent_codes_without_table_poison_under_trace():
    """Traced misuse cannot raise: the structural guard must poison the
    affected entries (loudly constant) instead of returning plausible
    unresolved partials — ADVICE r5 / ISSUE satellite."""
    import jax

    from fishnet_tpu.nnue import spec as _spec
    from fishnet_tpu.ops.ft_gather import _POISON_ACC

    ft_w, ft_b, idx = _fixture(batch=3)
    parent = jnp.asarray(np.array([-1, -4, -1], np.int32))

    @jax.jit
    def run(w, b, i, p):
        return ft_accumulate(
            w, b, i, use_pallas=False, delta_base=_spec.DELTA_BASE, parent=p
        )

    acc = np.asarray(run(ft_w, ft_b, idx, parent))
    assert (acc[1] == _POISON_ACC).all()
    assert (acc[0] != _POISON_ACC).any() and (acc[2] != _POISON_ACC).any()


def test_persistent_codes_without_material_poison_scores_under_trace():
    import jax

    from fishnet_tpu.nnue import spec as _spec
    from fishnet_tpu.nnue.jax_eval import evaluate_batch, params_from_weights
    from fishnet_tpu.nnue.weights import NnueWeights

    params = params_from_weights(NnueWeights.random(seed=5))
    feats = jnp.asarray(
        np.full((3, 2, _spec.MAX_ACTIVE_FEATURES), _spec.NUM_FEATURES, np.uint16)
    )
    buckets = jnp.zeros((3,), jnp.int32)
    parent = jnp.asarray(np.array([-1, -4, -1], np.int32))

    @jax.jit
    def run(p, f, b, par):
        return evaluate_batch(p, f, b, par)

    vals = np.asarray(run(params, feats, buckets, parent))
    assert abs(int(vals[1])) > 10**6  # ~2^24 cp: unmistakably poisoned
    assert abs(int(vals[0])) < 10**6 and abs(int(vals[2])) < 10**6


def test_persistent_codes_concrete_without_material_raise_structurally():
    """The eager-path twin of the poison tests above: a CONCRETE batch
    carrying a persistent anchor code with neither host material nor a
    device-resolved psqt must fail structurally in the network head —
    the in-batch-only PSQT fallback there cannot resolve table refs and
    would otherwise return plausible garbage (jax_eval
    _evaluate_from_acc)."""
    from fishnet_tpu.nnue import spec as _spec
    from fishnet_tpu.nnue.jax_eval import (
        _evaluate_from_acc,
        params_from_weights,
    )
    from fishnet_tpu.nnue.weights import NnueWeights

    params = params_from_weights(NnueWeights.random(seed=5))
    feats = jnp.asarray(
        np.full((3, 2, _spec.MAX_ACTIVE_FEATURES), _spec.NUM_FEATURES, np.int32)
    )
    buckets = jnp.zeros((3,), jnp.int32)
    parent = jnp.asarray(np.array([-1, -4, -1], np.int32))
    acc = jnp.zeros((3, 2, _spec.L1), jnp.int32)
    with pytest.raises(ValueError, match="persistent anchor codes"):
        _evaluate_from_acc(params, acc, feats, buckets, parent, None)
