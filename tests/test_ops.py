"""Pallas kernel parity tests (interpreter mode on CPU; the same kernel
is exercised on real TPU hardware by bench/verify runs)."""

import numpy as np
import pytest

import jax.numpy as jnp

from fishnet_tpu.ops.ft_gather import _xla_ft_accumulate, ft_accumulate


def _fixture(n_features=512, l1=1024, batch=5, active=32, seed=0):
    rng = np.random.default_rng(seed)
    ft_w = jnp.asarray(
        np.vstack(
            [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
        ).astype(np.int16)
    )
    ft_b = jnp.asarray(rng.integers(-100, 100, (l1,)).astype(np.int16))
    idx = rng.integers(0, n_features, (batch, 2, active)).astype(np.int32)
    # Pad a few slots with the sentinel row like real feature extraction.
    idx[:, :, active - 3 :] = n_features
    return ft_w, ft_b, jnp.asarray(idx)


def test_pallas_ft_gather_matches_xla_interpret():
    ft_w, ft_b, idx = _fixture()
    ref = np.asarray(_xla_ft_accumulate(ft_w, ft_b, idx))
    got = np.asarray(ft_accumulate(ft_w, ft_b, idx, interpret=True))
    assert np.array_equal(ref, got)


def test_pallas_ft_gather_sentinel_rows_are_noops():
    ft_w, ft_b, _ = _fixture()
    n = ft_w.shape[0] - 1
    idx = jnp.full((3, 2, 32), n, dtype=jnp.int32)  # all padding
    got = np.asarray(ft_accumulate(ft_w, ft_b, idx, interpret=True))
    expected = np.broadcast_to(np.asarray(ft_b, np.int32), got.shape)
    assert np.array_equal(got, expected)


def test_auto_selection_falls_back_on_cpu():
    # On the CPU test backend the auto path must use XLA (and agree).
    ft_w, ft_b, idx = _fixture(batch=2)
    auto = np.asarray(ft_accumulate(ft_w, ft_b, idx))
    ref = np.asarray(_xla_ft_accumulate(ft_w, ft_b, idx))
    assert np.array_equal(auto, ref)


def test_evaluate_batch_still_matches_cpp_oracle_path():
    # evaluate_batch routes through ft_accumulate now; the existing nnue
    # parity suite (test_nnue.py) covers full-score parity — here just a
    # smoke check that the plumbing holds shapes.
    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.jax_eval import evaluate_batch, params_from_weights
    from fishnet_tpu.nnue.weights import NnueWeights

    params = params_from_weights(NnueWeights.random(seed=1))
    rng = np.random.default_rng(2)
    idx = rng.integers(
        0, spec.NUM_FEATURES + 1, (4, 2, spec.MAX_ACTIVE_FEATURES)
    ).astype(np.int32)
    buckets = rng.integers(0, spec.NUM_PSQT_BUCKETS, (4,)).astype(np.int32)
    out = np.asarray(evaluate_batch(params, jnp.asarray(idx), jnp.asarray(buckets)))
    assert out.shape == (4,)
    assert np.all(np.abs(out) < 10_000_000)


@pytest.mark.parametrize("batch", [1, 3, 300])
def test_pallas_chunking_boundaries(batch):
    # _CHUNK = 256: cover under, at-boundary-crossing, and tiny batches.
    ft_w, ft_b, idx = _fixture(batch=batch, l1=1024)
    ref = np.asarray(_xla_ft_accumulate(ft_w, ft_b, idx))
    got = np.asarray(ft_accumulate(ft_w, ft_b, idx, interpret=True))
    assert np.array_equal(ref, got)


def _block_batch(n_features, active, n_blocks, block, rng):
    """Anchor-protocol batch: each block is one full entry followed by
    delta children referencing it (the most recent preceding full
    entry), with random perspective swaps — the shape the native pool
    emits (cpp/src/pool.cpp evaluate_block)."""
    from fishnet_tpu.ops.ft_gather import _DELTA_SLOTS

    delta_base = n_features + 1
    batch = n_blocks * block
    idx = np.full((batch, 2, active), n_features, np.int32)
    parent = np.full((batch,), -1, np.int32)
    for s in range(0, batch, block):
        idx[s, :, : active - 3] = rng.integers(0, n_features, (2, active - 3))
        for j in range(1, block):
            e = s + j
            swap = int(rng.integers(0, 2))
            parent[e] = (s << 1) | swap
            for p in range(2):
                n_add = int(rng.integers(0, _DELTA_SLOTS + 1))
                n_rem = int(rng.integers(0, _DELTA_SLOTS + 1))
                idx[e, p, :n_add] = rng.integers(0, n_features, n_add)
                idx[e, p, _DELTA_SLOTS : _DELTA_SLOTS + n_rem] = (
                    delta_base + rng.integers(0, n_features, n_rem)
                )
                idx[e, p, _DELTA_SLOTS + n_rem : 2 * _DELTA_SLOTS] = (
                    delta_base + n_features
                )
    return jnp.asarray(idx), jnp.asarray(parent), delta_base


def test_pallas_anchored_resolution_interpret(monkeypatch):
    """Anchored (in-VMEM running anchor) delta resolution must agree
    bit-exactly with the XLA explicit-index fallback, including across
    pallas-call chunk boundaries (the carry-in path): shrink _CHUNK so
    blocks straddle chunks and children must resolve against an anchor
    computed by the PREVIOUS pallas call."""
    from fishnet_tpu.ops import ft_gather

    monkeypatch.setattr(ft_gather, "_CHUNK", 8)
    n_features, l1, active = 512, 1024, 32
    rng = np.random.default_rng(11)
    ft_w = jnp.asarray(
        np.vstack(
            [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
        ).astype(np.int16)
    )
    ft_b = jnp.asarray(rng.integers(-100, 100, (l1,)).astype(np.int16))
    # Blocks of 5 against chunks of 8: entries 8-9 (etc.) are deltas
    # whose anchor lives in the previous chunk.
    idx, parent, delta_base = _block_batch(n_features, active, 4, 5, rng)
    ref = np.asarray(
        ft_gather.ft_accumulate(
            ft_w, ft_b, idx, use_pallas=False,
            delta_base=delta_base, parent=parent,
        )
    )
    got = np.asarray(
        ft_gather.ft_accumulate(
            ft_w, ft_b, idx, interpret=True,
            delta_base=delta_base, parent=parent,
        )
    )
    assert np.array_equal(ref, got)


def test_pallas_sparse_delta_mode_interpret():
    """The kernel's SPARSE mode (mode-predicated transfers, removal-slot
    index decode, adds-minus-removes reduce) must agree with the XLA
    signed fallback in interpreter mode — the only way to execute this
    branch offline before it serves real TPU traffic."""
    from fishnet_tpu.ops.ft_gather import _DELTA_SLOTS

    n_features, l1, active = 512, 1024, 32
    delta_base = n_features + 1
    rng = np.random.default_rng(3)
    ft_w = jnp.asarray(
        np.vstack(
            [rng.integers(-200, 200, (n_features, l1)), np.zeros((1, l1))]
        ).astype(np.int16)
    )
    ft_b = jnp.asarray(rng.integers(-100, 100, (l1,)).astype(np.int16))

    batch = 8
    idx = np.full((batch, 2, active), n_features, np.int32)
    sparse = np.zeros((batch,), bool)
    for b in range(batch):
        if b % 2 == 0:  # dense entry
            idx[b, :, : active - 3] = rng.integers(
                0, n_features, (2, active - 3)
            )
        else:  # sparse delta entry: adds + encoded removals, region-padded
            sparse[b] = True
            for p in range(2):
                n_add = int(rng.integers(0, _DELTA_SLOTS + 1))
                n_rem = int(rng.integers(0, _DELTA_SLOTS + 1))
                idx[b, p, :n_add] = rng.integers(0, n_features, n_add)
                idx[b, p, _DELTA_SLOTS : _DELTA_SLOTS + n_rem] = (
                    delta_base + rng.integers(0, n_features, n_rem)
                )
                idx[b, p, _DELTA_SLOTS + n_rem : 2 * _DELTA_SLOTS] = (
                    delta_base + n_features
                )

    ref = np.asarray(
        _xla_ft_accumulate(ft_w, ft_b, jnp.asarray(idx), delta_base=delta_base)
    )
    got = np.asarray(
        ft_accumulate(
            ft_w, ft_b, jnp.asarray(idx),
            interpret=True, delta_base=delta_base,
            sparse=jnp.asarray(sparse),
        )
    )
    assert np.array_equal(ref, got)
