"""Double-buffered async dispatch (the PR 6 tentpole): sync-vs-async
bit-identical analyses across the psqt_path rungs, ping-pong donation
correctness (never more than DEPTH dispatches in flight, staging slots
never reused while unmaterialized), failure semantics under async
(``service.device_step`` faults still degrade the ladder and reach the
owning driver), deterministic wire-diet planner units (cross-segment
eval-dedup + anchor placement), and an overlap smoke proving
transport/compute overlap actually happens (overlap_ratio > 0, the
dispatch_issue/dispatch_wait span families recorded)."""

import asyncio
import os
import threading
import time

import numpy as np
import pytest

from fishnet_tpu.chess.core import NativeCoreError
from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.ops.ft_gather import plan_segment_dedup
from fishnet_tpu.resilience import accounting, faults
from fishnet_tpu.resilience.supervisor import ServiceSupervisor
from fishnet_tpu.search.service import (
    SearchService,
    _AsyncDispatchPipeline,
    _CoalesceTicket,
    _FusedValues,
)
from fishnet_tpu.utils.logger import Logger


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    faults.clear()
    accounting.clear()


# -- harness (test_coalesce's gated smoke, parameterized) ---------------------


_SMOKE_FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/4P3/5N2/PPPP1PPP/RNBQKB1R w KQkq - 2 3",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "4rrk1/pp1n3p/3q2pQ/2p1pb2/2PP4/2P3N1/P2B2PP/4RRK1 b - - 7 19",
    "r3r1k1/2p2ppp/p1p1bn2/8/1q2P3/2NPQN2/PPP3PP/R4RK1 b - - 2 15",
    "2rq1rk1/1p3ppp/p2p1n2/2bPp3/4P1b1/2N2N2/PPQ1BPPP/R1B2RK1 w - - 0 12",
    "r1bqk2r/ppp2ppp/2np1n2/2b1p3/2B1P3/2PP1N2/PP3PPP/RNBQK2R w KQkq - 0 6",
    "r2q1rk1/ppp2ppp/2npbn2/2b1p3/4P3/2PP1NN1/PPB2PPP/R1BQ1RK1 w - - 6 9",
]


class _GatedService(SearchService):
    """SearchService whose driver parks after warmup until the gate
    opens — every smoke submission lands in ONE drain pass, making the
    whole schedule a deterministic function of the submission sequence
    (test_coalesce's discipline; with bit-identical eval values the
    async and sync runs then walk the exact same search trees)."""

    def __init__(self, *args, **kwargs):
        self.gate = threading.Event()
        super().__init__(*args, **kwargs)

    def warmup(self):
        super().warmup()
        self.gate.wait()


def _smoke_run(weights, fens=None, nodes=200, psqt_path=None, mutate=None):
    # Default workload sized for tier-1 wall clock: 6 positions x 200
    # nodes still drives multi-group coalesced traffic through every
    # entry kind while a full smoke stays well under 10 s on one core.
    fens = _SMOKE_FENS[:6] if fens is None else fens
    from fishnet_tpu.search import eval_cache

    # Cold-start the process eval cache: back-to-back runs of the same
    # FENs would otherwise whole-batch-skip dispatches and skew the
    # eval_steps/overlap comparisons (analyses stay bit-identical).
    eval_cache.reset_cache()
    svc = _GatedService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1, psqt_path=psqt_path,
    )
    try:
        # Pin speculation so TT insertions are schedule-deterministic.
        svc.set_prefetch(0, adaptive=False)
        if mutate is not None:
            mutate(svc)

        async def go():
            tasks = [
                asyncio.ensure_future(svc.search(fen, [], nodes=nodes))
                for fen in fens
            ]
            await asyncio.sleep(0.3)  # let every submission queue
            svc.gate.set()
            return await asyncio.gather(*tasks)

        results = asyncio.run(go())
        analyses = [
            (
                r.best_move, r.depth, r.nodes,
                tuple(
                    (l.multipv, l.depth, l.is_mate, l.value, tuple(l.pv))
                    for l in r.lines
                ),
            )
            for r in results
        ]
        meta = {
            "async": svc._async_pipe is not None,
            "overlap_ratio": (
                svc._async_pipe.overlap_ratio()
                if svc._async_pipe is not None else 0.0
            ),
        }
        return analyses, svc.counters(), meta
    finally:
        svc.gate.set()  # never leave the driver parked on a failure
        svc.close()


# -- sync vs async bit-identical analyses (all rungs) -------------------------


@pytest.mark.parametrize("rung", ["xla", "host-material"])
def test_async_parity_smoke(rung, monkeypatch):
    """The tentpole invariant: the async double-buffered pipeline is a
    pure scheduling change — analyses are bit-identical to the
    synchronous inline flush (FISHNET_NO_ASYNC=1), per rung."""
    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")
    a, ca, ma = _smoke_run(weights, psqt_path=rung)
    assert ma["async"], "async pipeline should be on by default"
    monkeypatch.setenv("FISHNET_NO_ASYNC", "1")
    b, cb, mb = _smoke_run(weights, psqt_path=rung)
    assert not mb["async"]
    assert a == b, "async dispatch changed analysis output"
    assert ca["eval_steps"] == cb["eval_steps"]


def test_async_parity_smoke_fused(monkeypatch):
    """The fused rung (Pallas interpreter off-TPU — hence the reduced
    workload) walks the same trees sync and async."""
    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "2")
    kw = dict(fens=_SMOKE_FENS[:4], nodes=120, psqt_path="fused")
    a, _, ma = _smoke_run(weights, **kw)
    assert ma["async"]
    monkeypatch.setenv("FISHNET_NO_ASYNC", "1")
    b, _, mb = _smoke_run(weights, **kw)
    assert not mb["async"]
    assert a == b, "async dispatch changed analysis output (fused rung)"


def test_no_async_env_disables_pipeline(monkeypatch):
    monkeypatch.setenv("FISHNET_NO_ASYNC", "1")
    svc = SearchService(
        weights=NnueWeights.random(seed=3), pool_slots=8,
        batch_capacity=256, tt_bytes=4 << 20, backend="jax",
        pipeline_depth=4, driver_threads=1,
    )
    try:
        assert svc._coalescer is not None
        assert svc._async_pipe is None
    finally:
        svc.close()


def test_single_group_service_builds_no_pipeline():
    # No coalescer (one group) -> nothing to pipeline behind.
    svc = SearchService(
        weights=NnueWeights.random(seed=3), pool_slots=8,
        batch_capacity=64, tt_bytes=4 << 20, backend="jax",
    )
    try:
        assert svc._coalescer is None
        assert svc._async_pipe is None
    finally:
        svc.close()


# -- ping-pong donation correctness -------------------------------------------


class _Blocker:
    """An array-like whose materialization blocks until released —
    stands in for an in-flight device dispatch."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()

    def __array__(self, dtype=None, copy=None):
        self.entered.set()
        self.release.wait(10)
        return np.zeros(4, np.int32)


class _StubCoalescer:
    def __init__(self):
        self._lock = threading.Lock()
        self.executed = []

    def _execute(self, tickets, defer_cost=False):
        with self._lock:
            self.executed.append(tickets)
        for tk in tickets:
            tk.done.set()


class _StubSvc:
    def __init__(self):
        self._coalescer = _StubCoalescer()


def test_ping_pong_depth_bounds_inflight_dispatches():
    """Dispatch N+2 must not stage until dispatch N has materialized:
    its staging slot (N % DEPTH) still belongs to an in-flight wire."""
    svc = _StubSvc()
    pipe = _AsyncDispatchPipeline(svc)
    blockers = [_Blocker() for _ in range(3)]
    tks = []

    def n_exec():
        with svc._coalescer._lock:
            return len(svc._coalescer.executed)

    def wait_exec(n, timeout=5.0):
        deadline = time.monotonic() + timeout
        while n_exec() < n and time.monotonic() < deadline:
            time.sleep(0.005)
        return n_exec()

    try:
        for b in blockers:
            tk = _CoalesceTicket(0, 1, 4)
            tk.values = _FusedValues(b)
            tks.append(tk)
            assert pipe.submit([tk])
        assert wait_exec(2) == 2
        assert blockers[0].entered.wait(5)
        time.sleep(0.2)  # every chance for the pack worker to misbehave
        assert n_exec() == 2, "third dispatch staged while two in flight"
        assert pipe.inflight() == 2
        blockers[0].release.set()  # dispatch 0 materializes, slot 0 frees
        assert wait_exec(3) == 3
        blockers[1].release.set()
        blockers[2].release.set()
        for tk in tks:
            assert tk.done.wait(5)
            assert tk.error is None
    finally:
        for b in blockers:
            b.release.set()
        pipe.close()


def test_submit_after_close_reports_down():
    """A downed pipeline refuses batches (the coalescer then runs its
    inline synchronous flush, so shutdown never strands a ticket)."""
    pipe = _AsyncDispatchPipeline(_StubSvc())
    pipe.close()
    assert not pipe.submit([_CoalesceTicket(0, 1, 4)])


# -- failure semantics under async --------------------------------------------


@pytest.mark.anyio
async def test_device_step_fault_under_async_degrades_ladder():
    """The ``service.device_step`` fault site still fires on the driver
    thread with the async pipeline up: the error reaches the owner, the
    service reads dead, and the supervisor degrades one rung."""
    weights = NnueWeights.random(seed=21)

    def builder(rung):
        return SearchService(
            weights=weights, pool_slots=8, batch_capacity=256,
            tt_bytes=8 << 20, backend="jax", psqt_path=rung,
            pipeline_depth=4, driver_threads=1,
        )

    sup = ServiceSupervisor(
        builder, start_rung="xla", degrade_after=1, logger=Logger()
    )
    fresh = "rnbqkb1r/pppppppp/5n2/8/3P4/8/PPP1PPPP/RNBQKBNR w KQkq - 1 2"
    svc = sup.build()
    try:
        assert svc._async_pipe is not None
        faults.install("service.device_step:nth=1:crash")
        with pytest.raises(NativeCoreError):
            await svc.search(fresh, [], depth=3)
        faults.clear()
        assert not svc.is_alive()
    finally:
        svc.close()
    svc2 = sup.build()
    try:
        assert sup.rung == "host-material"  # degraded below "xla"
        r = await svc2.search(fresh, [], depth=2)
        assert r.best_move is not None
    finally:
        svc2.close()


# -- cross-segment eval-dedup planner (deterministic units) -------------------


def _pers_code(aid, is_delta, swap=0):
    return -(2 + ((aid << 2) | (2 if is_delta else 0) | swap))


def _payload(pid):
    rng = np.random.default_rng(1000 + pid)
    return rng.integers(0, spec.NUM_FEATURES, (4, 2, 8)).astype(np.uint16)


def _delta_payload(pid):
    rng = np.random.default_rng(2000 + pid)
    row = np.full((1, 2, 8), spec.NUM_FEATURES, np.uint16)
    row[0, :, :2] = rng.integers(0, spec.NUM_FEATURES, (2, 2))
    row[0, :, 4] = spec.DELTA_BASE + rng.integers(0, spec.NUM_FEATURES, (2,))
    row[0, :, 5:] = spec.DELTA_BASE + spec.NUM_FEATURES
    return row


def _dedup_seg(plan, size=8):
    """One segment's planner inputs from an entry plan. Items:
    ("full", payload) plain full; ("store", aid, payload) full anchor
    seed; ("pers", aid, payload) persistent anchor delta;
    ("inbatch", ref) in-batch delta. Equal payload ids produce
    byte-identical feature blocks."""
    parent = np.full(size, -1, np.int32)
    buckets = np.zeros(size, np.int32)
    offsets = np.zeros(size, np.int32)
    chunks, rows = [], 0
    for i, item in enumerate(plan):
        offsets[i] = rows
        kind = item[0]
        if kind == "full":
            parent[i] = -1
            chunks.append(_payload(item[1]))
            rows += 4
        elif kind == "store":
            parent[i] = _pers_code(item[1], False)
            chunks.append(_payload(item[2]))
            rows += 4
        elif kind == "pers":
            parent[i] = _pers_code(item[1], True)
            chunks.append(_delta_payload(item[2]))
            rows += 1
        else:  # in-batch delta
            parent[i] = item[1] << 1
            chunks.append(_delta_payload(99))
            rows += 1
    packed = (
        np.concatenate(chunks)
        if chunks else np.zeros((0, 2, 8), np.uint16)
    )
    return parent, buckets, offsets, packed, len(plan)


def _plan_args(*segs):
    return (
        [s[0] for s in segs],  # parents
        [s[1] for s in segs],  # buckets
        [s[2] for s in segs],  # offsets
        [s[4] for s in segs],  # ns
        [s[3] for s in segs],  # packed
    )


def test_dedup_planner_drops_cross_segment_duplicate():
    s0 = _dedup_seg([("full", 1), ("full", 2)])
    s1 = _dedup_seg([("full", 3), ("full", 2), ("full", 4)])
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], [1]]
    assert refs == [[], [0]]  # most recent preceding kept anchor
    assert pairs == [(1, 1, 0, 1)]  # value restored from the original


def test_dedup_planner_keeps_consumed_fulls():
    # Segment 1's duplicate full anchors an in-batch delta: dropping it
    # would orphan the chain, so it must be kept.
    s0 = _dedup_seg([("full", 2)])
    s1 = _dedup_seg([("full", 3), ("full", 2), ("inbatch", 1)])
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], []] and pairs == []


def test_dedup_planner_never_drops_first_entry():
    # Every group batch STARTS with an anchor (wire invariant): entry 0
    # stays even when it duplicates an earlier segment's entry.
    s0 = _dedup_seg([("full", 2)])
    s1 = _dedup_seg([("full", 2), ("full", 5)])
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], []] and pairs == []


def test_dedup_planner_never_drops_persistent_entries():
    # A persistent-store entry seeds the anchor table: not removable
    # even when its feature block matches an earlier full.
    s0 = _dedup_seg([("full", 7)])
    s1 = _dedup_seg([("full", 3), ("store", 1, 7)])
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], []] and pairs == []


def test_dedup_planner_matches_store_originals():
    # ...but a plain full DUPLICATING a store's block is droppable.
    s0 = _dedup_seg([("store", 0, 7)])
    s1 = _dedup_seg([("full", 8), ("full", 7)])
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], [1]]
    assert refs == [[], [0]]
    assert pairs == [(1, 1, 0, 0)]


def test_dedup_planner_bucket_distinguishes():
    s0 = _dedup_seg([("full", 2)])
    s1 = _dedup_seg([("full", 3), ("full", 2)])
    s1[1][1] = 5  # same rows, different layer-stack bucket
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], []] and pairs == []


def test_dedup_planner_refs_skip_dropped_anchors():
    # Two duplicates in a row: the second's ref must point at the last
    # KEPT anchor, not at the first duplicate (which is gone).
    s0 = _dedup_seg([("full", 2)])
    s1 = _dedup_seg([("full", 5), ("full", 2), ("full", 2)])
    drops, refs, pairs = plan_segment_dedup(*_plan_args(s0, s1))
    assert drops == [[], [1, 2]]
    assert refs == [[], [0, 0]]
    assert pairs == [(1, 1, 0, 0), (1, 2, 0, 0)]


def test_dedup_planner_is_deterministic():
    s0 = _dedup_seg([("full", 1), ("full", 2), ("inbatch", 0)])
    s1 = _dedup_seg([("full", 2), ("full", 1), ("full", 2)])
    first = plan_segment_dedup(*_plan_args(s0, s1))
    second = plan_segment_dedup(*_plan_args(s0, s1))
    assert first == second


# -- dedup staging end-to-end (values bit-identical, garbage restored) --------


def test_segmented_dedup_restores_values_bit_identical():
    """Staging a fused dispatch with dedup ON yields values
    bit-identical to dedup OFF: the duplicate ships as a one-row
    sentinel delta, computes garbage on device, and _FusedValues
    restores its true value from the original at materialize time."""
    weights = NnueWeights.random(seed=5)
    svc = SearchService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=4 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1, psqt_path="xla",
    )
    try:
        svc.warmup()  # serialize vs the driver's own warmup dispatches
        rng = np.random.default_rng(3)
        size = svc._eval_sizes[0]

        def fill(g, plan):
            rows = 0
            for i, item in enumerate(plan):
                svc._offset_buf[g][i] = rows
                if item[0] == "full":
                    svc._parent_buf[g][i] = -1
                    svc._packed_buf[g][rows : rows + 4] = _payload(item[1])
                    rows += 4
                else:  # in-batch delta
                    svc._parent_buf[g][i] = item[1] << 1
                    svc._packed_buf[g][rows : rows + 1] = _delta_payload(99)
                    rows += 1
            svc._bucket_buf[g][: len(plan)] = 0
            return len(plan), rows

        n0, rows0 = fill(0, [("full", 1), ("inbatch", 0), ("full", 2)])
        n1, rows1 = fill(1, [("full", 3), ("full", 2), ("inbatch", 0)])

        def dispatch():
            tks = [_CoalesceTicket(0, n0, rows0),
                   _CoalesceTicket(1, n1, rows1)]
            svc._dispatch_segmented(tks)
            return tks

        assert svc._dedup_fused
        tks_on = dispatch()
        v_on = tks_on[0].values.materialize().copy()
        assert svc.counters()["fused_dedup"] == 1

        svc._dedup_fused = False
        tks_off = dispatch()
        v_off = tks_off[0].values.materialize()
        np.testing.assert_array_equal(v_on, v_off)
        # The duplicate (segment 1 entry 1) carries its original's value.
        assert v_on[1 * size + 1] == v_on[0 * size + 2]
    finally:
        svc.close()


def test_dedup_smoke_parity(monkeypatch):
    """Identical searches stepping in lockstep across sibling groups
    maximize cross-segment duplicate pressure; the dedup pass must not
    change any analysis vs FISHNET_NO_DEDUP=1. (Under anchor-table
    traffic the duplicates are overwhelmingly persistent STORE entries
    — table seeds the planner correctly refuses to drop, see
    doc/wire-format.md — so this smoke pins the no-misfire side; the
    staging unit above pins the retire side.)"""
    weights = NnueWeights.random(seed=11)
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")
    fens = [_SMOKE_FENS[0]] * 4 + [_SMOKE_FENS[1]] * 4
    a, ca, _ = _smoke_run(weights, fens=fens)
    monkeypatch.setenv("FISHNET_NO_DEDUP", "1")
    b, cb, _ = _smoke_run(weights, fens=fens)
    assert a == b, "eval-dedup changed analysis output"
    assert cb["fused_dedup"] == 0
    assert ca["fused_dedup"] >= 0  # organic anchored traffic: often 0


# -- anchor-placement policy (deterministic, bit-exact) -----------------------


@pytest.fixture(scope="module")
def baseline_smoke():
    """One shared async default-rung smoke (seed-7 weights, width 4):
    the baseline half of both placement tests below, run once."""
    old = os.environ.get("FISHNET_COALESCE_WIDTH")
    os.environ["FISHNET_COALESCE_WIDTH"] = "4"
    try:
        result = _smoke_run(NnueWeights.random(seed=7))
    finally:
        if old is None:
            os.environ.pop("FISHNET_COALESCE_WIDTH", None)
        else:
            os.environ["FISHNET_COALESCE_WIDTH"] = old
    return result


def test_anchor_placement_is_deterministic(baseline_smoke, monkeypatch):
    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")
    a1, c1, _ = baseline_smoke
    a2, c2, _ = _smoke_run(weights)
    assert a1 == a2
    for key in ("eval_steps", "delta_evals", "anchor_deltas", "nodes"):
        assert c1[key] == c2[key], key


def test_anchor_placement_off_is_bit_identical(baseline_smoke, monkeypatch):
    """Placement only reorders entries within an emission block (values
    are exact integers either way): analyses must not move."""
    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")
    monkeypatch.setenv("FISHNET_NO_ANCHOR_PLACEMENT", "1")
    b, _, _ = _smoke_run(weights)
    assert baseline_smoke[0] == b, "anchor placement changed analysis output"


# -- overlap smoke ------------------------------------------------------------


class _SlowValues:
    """Wraps a dispatched array; materializing costs an extra sleep,
    standing in for wire transport on a tunneled link."""

    def __init__(self, arr, delay):
        self._arr = arr
        self._delay = delay

    def __array__(self, dtype=None, copy=None):
        time.sleep(self._delay)
        return np.asarray(self._arr)


def test_overlap_smoke(monkeypatch):
    """With materialization slowed to transport-like latencies, the
    double buffer must actually overlap dispatches: overlap_ratio > 0
    live (counters + gauge inputs) and via the span flight recorder
    (bench.py's overlap report)."""
    from fishnet_tpu import telemetry
    from fishnet_tpu.telemetry.spans import RECORDER

    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "2")
    telemetry.enable()
    try:
        def mutate(svc):
            orig_seg = svc._dispatch_segmented
            orig_solo = svc._dispatch_eval

            def slow_segmented(tickets):
                orig_seg(tickets)
                fv = tickets[0].values
                fv._arr = _SlowValues(fv._arr, 0.05)

            def slow_solo(group, n, rows):
                values, acct = orig_solo(group, n, rows)
                return _SlowValues(values, 0.05), acct

            svc._dispatch_segmented = slow_segmented
            svc._dispatch_eval = slow_solo

        weights = NnueWeights.random(seed=7)
        _, counters, meta = _smoke_run(weights, mutate=mutate)
        assert meta["async"]
        assert counters["overlap_busy_us"] > 0
        assert counters["overlap_dual_us"] > 0
        assert meta["overlap_ratio"] > 0

        stages = RECORDER.stages_seen()
        assert "dispatch_issue" in stages and "dispatch_wait" in stages

        from bench import overlap_report_from_spans

        report = overlap_report_from_spans()
        assert report["dispatches_paired"] > 0
        assert report["overlap_ratio"] > 0
    finally:
        telemetry.disable()
