"""A minimal scripted UCI engine for driver tests.

Speaks just enough UCI to exercise fishnet_tpu.engine.uci: handshake,
options, position/go, multipv info lines, bestmove. Behavior toggles via
env vars:

* FAKE_UCI_DIE_ON_GO=1   — exit silently when `go` arrives (crash test);
* FAKE_UCI_NO_SCORE=1    — send bestmove without any info score
  (protocol-violation test);
* FAKE_UCI_MATE=1        — report a terminal position (`score mate 0`,
  no pv, `bestmove (none)`), as Stockfish does for checkmate/stalemate.
"""

import os
import sys


def say(line):
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


def main():
    multipv = 1
    variant = "chess"
    last_go = ""
    for raw in sys.stdin:
        line = raw.strip()
        tokens = line.split()
        if not tokens:
            continue
        cmd = tokens[0]
        if cmd == "uci":
            say("id name FakeUCI 1.0")
            say("option name Hash type spin default 16 min 1 max 1024")
            say("option name MultiPV type spin default 1 min 1 max 500")
            say("option name Skill Level type spin default 20 min -9 max 20")
            say("option name Use NNUE type check default true")
            say("option name UCI_Chess960 type check default false")
            say("option name UCI_AnalyseMode type check default false")
            say("option name UCI_Variant type combo default chess var chess var atomic var antichess")
            say("uciok")
        elif cmd == "isready":
            say("readyok")
        elif cmd == "setoption":
            # setoption name <Name...> value <v>
            if "value" in tokens:
                vi = tokens.index("value")
                name = " ".join(tokens[2:vi]).lower()
                value = " ".join(tokens[vi + 1 :])
                if name == "multipv":
                    multipv = int(value)
                elif name == "uci_variant":
                    variant = value
        elif cmd in ("ucinewgame", "position"):
            pass
        elif cmd == "go":
            last_go = line
            if os.environ.get("FAKE_UCI_DIE_ON_GO"):
                sys.exit(3)
            if os.environ.get("FAKE_UCI_NO_SCORE"):
                say("bestmove e2e4")
                continue
            if os.environ.get("FAKE_UCI_MATE"):
                say("info depth 0 score mate 0")
                say("bestmove (none)")
                continue
            moves = ["e2e4", "d2d4", "g1f3", "c2c4"]
            for depth in (1, 2, 3):
                for pv in range(1, multipv + 1):
                    say(
                        f"info depth {depth} seldepth {depth} multipv {pv} "
                        f"score cp {10 * depth - 5 * (pv - 1)} nodes {1000 * depth} "
                        f"nps 500000 time {2 * depth} pv {moves[pv - 1]} e7e5"
                    )
            # An upperbound line must be ignored by the parser.
            say("info depth 4 multipv 1 score cp 99 upperbound nodes 4000 nps 500000 time 9 pv e2e4")
            say(f"info string variant={variant} go=[{last_go}]")
            say("bestmove e2e4 ponder e7e5")
        elif cmd == "quit":
            return


if __name__ == "__main__":
    main()
