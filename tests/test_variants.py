"""Variant rules (the reference's Fairy-Stockfish tier, src/logger.rs:192-203,
src/queue.rs:530-539): perft validation against Fairy-Stockfish's published
vectors, per-variant rule deltas, FEN round-trips, and batched variant
searches through the SearchService (HCE eval on the host)."""

import pytest

from fishnet_tpu.chess.board import Board, variant_supported
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.protocol.types import Variant
from fishnet_tpu.search.service import SearchService

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
HORDE_START = "rnbqkbnr/pppppppp/8/1PP2PP1/PPPPPPPP/PPPPPPPP/PPPPPPPP/PPPPPPPP w kq - 0 1"
RK_START = "8/8/8/8/8/8/krbnNBRK/qrbnNBRQ w - - 0 1"


def test_all_variants_supported():
    for v in Variant:
        assert variant_supported(v), v


# -- perft (depths kept modest; the full d5/d6 suite runs in cpp/perft) ----

PERFTS = [
    (Variant.ANTICHESS, STARTPOS.replace(" KQkq", " -"), 4, 153299),
    (Variant.ATOMIC, STARTPOS, 4, 197326),
    (Variant.CRAZYHOUSE, STARTPOS.replace("NR w", "NR[] w"), 4, 197281),
    (Variant.HORDE, HORDE_START, 5, 265223),
    (Variant.RACING_KINGS, RK_START, 4, 296242),
    (Variant.THREE_CHECK, STARTPOS + " +0+0", 4, 197281),
    (Variant.KING_OF_THE_HILL, STARTPOS, 4, 197281),
]


@pytest.mark.parametrize("variant,fen,depth,expected", PERFTS,
                         ids=[p[0].value for p in PERFTS])
def test_variant_perft(variant, fen, depth, expected):
    assert Board(fen, variant).perft(depth) == expected


# -- antichess -------------------------------------------------------------


def test_antichess_forced_capture():
    b = Board(STARTPOS.replace(" KQkq", " -"), Variant.ANTICHESS)
    b.push_uci("e2e3")
    b.push_uci("b7b5")
    # Bxb5 is the only capture, so it is the only legal move.
    assert b.legal_moves() == ["f1b5"]


def test_antichess_king_promotion_and_win():
    b = Board("8/P7/8/8/8/8/8/k7 w - - 0 1", Variant.ANTICHESS)
    assert "a7a8k" in b.legal_moves()
    # Losing all pieces wins: position where side to move has none.
    b2 = Board("8/8/8/8/8/8/8/k7 w - - 0 1", Variant.ANTICHESS)
    assert b2.outcome() == Board.VARIANT_WIN  # stm has no pieces -> wins? no:
    # white to move with NO pieces: no moves -> win for white in antichess.


# -- atomic ----------------------------------------------------------------


def test_atomic_explosion_removes_adjacent_non_pawns():
    # exd5 explodes: capturing pawn, captured knight, and the adjacent
    # knight on e5 all vanish; pawns elsewhere survive.
    b = Board("3k4/8/8/3nn3/4P3/8/8/3QK3 w - - 0 1", Variant.ATOMIC)
    b.push_uci("e4d5")
    fen = b.fen()
    assert fen.split()[0] == "3k4/8/8/8/8/8/8/3QK3"


def test_atomic_kings_cannot_capture():
    b = Board("3k4/8/8/8/8/8/4r3/4K3 w - - 0 1", Variant.ATOMIC)
    assert "e1e2" not in b.legal_moves()


def test_atomic_adjacent_kings_annul_check():
    # White king b2 "attacked" by the h2 rook, but kings touch: any quiet
    # move that keeps the contact is legal.
    b = Board("8/8/8/8/P7/2k5/1K5r/8 w - - 0 1", Variant.ATOMIC)
    assert "a4a5" in b.legal_moves()


def test_atomic_exploding_enemy_king_wins():
    b = Board("3k4/3q4/8/8/8/8/8/3QK3 w - - 0 1", Variant.ATOMIC)
    assert "d1d7" in b.legal_moves()
    b.push_uci("d1d7")
    assert b.outcome() == Board.VARIANT_LOSS  # black: king exploded


# -- horde -----------------------------------------------------------------


def test_horde_startpos_moves():
    assert len(Board(HORDE_START, Variant.HORDE).legal_moves()) == 8


def test_horde_first_rank_double_push():
    b = Board("rnbqkbnr/pppppppp/8/8/8/8/8/PPPPPPPP w kq - 0 1", Variant.HORDE)
    moves = b.legal_moves()
    assert "e1e3" in moves and "e1e2" in moves
    # ...but a first-rank double push grants no en-passant rights.
    b.push_uci("e1e3")
    assert b.fen().split()[3] == "-"


def test_horde_white_annihilated_loses():
    b = Board("4k3/8/8/8/8/8/8/8 w - - 0 1", Variant.HORDE)
    assert b.outcome() == Board.VARIANT_LOSS


# -- racing kings ----------------------------------------------------------


def test_racing_kings_no_checks_allowed():
    b = Board(RK_START, Variant.RACING_KINGS)
    for mv in b.legal_moves():
        nxt = b.copy()
        nxt.push_uci(mv)
        assert not nxt.is_check(), mv


def test_racing_kings_black_equalizing_move():
    # White king reached rank 8; black king one step away: game goes on.
    b = Board("7K/5k2/8/8/8/8/8/8 b - - 0 1", Variant.RACING_KINGS)
    assert b.outcome() == Board.ONGOING
    draw = b.copy()
    draw.push_uci("f7f8")
    assert draw.outcome() == Board.DRAW
    lose = b.copy()
    lose.push_uci("f7e6")
    assert lose.outcome() == Board.VARIANT_WIN  # white (to move) has won


def test_racing_kings_black_cannot_equalize():
    b = Board("7K/8/4k3/8/8/8/8/8 b - - 0 1", Variant.RACING_KINGS)
    assert b.outcome() == Board.VARIANT_LOSS


# -- crazyhouse ------------------------------------------------------------


def test_crazyhouse_pocket_and_drops():
    b = Board(STARTPOS.replace("NR w", "NR[] w"), Variant.CRAZYHOUSE)
    for mv in ["e2e4", "d7d5", "e4d5", "d8d5"]:
        b.push_uci(mv)
    # Both sides pocketed a pawn.
    assert "[Pp]" in b.fen()
    assert "P@e4" in b.legal_moves()


def test_crazyhouse_en_passant_fills_pocket():
    b = Board(STARTPOS.replace("NR w", "NR[] w"), Variant.CRAZYHOUSE)
    for mv in ["e2e4", "g8f6", "e4e5", "d7d5", "e5d6"]:  # exd6 e.p.
        b.push_uci(mv)
    assert "[P]" in b.fen()


def test_crazyhouse_promoted_piece_demotes_to_pawn():
    b = Board("k7/7P/8/8/8/8/7r/K7[] w - - 0 1", Variant.CRAZYHOUSE)
    b.push_uci("h7h8q")
    assert "Q~" in b.fen()
    b.push_uci("h2h8")
    fen = b.fen()
    assert "[p]" in fen and "~" not in fen


def test_crazyhouse_fen_roundtrip_promoted():
    fen = "k6Q~/8/8/8/8/8/8/K7[Rp] b - - 0 1"
    assert Board(fen, Variant.CRAZYHOUSE).fen() == fen


def test_crazyhouse_drop_blocks_mate():
    # Back-rank check; the only defenses include dropping a piece between
    # king and rook.
    b = Board("6k1/5ppp/8/8/8/8/8/4R1K1[n] b - - 0 1", Variant.CRAZYHOUSE)
    b.push_uci("g8h8")  # quiet
    b2 = Board("7k/5ppp/8/8/8/8/8/4R1K1[n] w - - 0 1", Variant.CRAZYHOUSE)
    b2.push_uci("e1e8")
    assert "N@f8" in b2.legal_moves() or "N@g8" in b2.legal_moves()


# -- three-check -----------------------------------------------------------


def test_three_check_fen_roundtrip():
    fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 3+3 0 1"
    assert Board(fen, Variant.THREE_CHECK).fen() == fen


def test_three_check_accepts_legacy_trailing_format():
    fen = STARTPOS + " +1+0"
    b = Board(fen, Variant.THREE_CHECK)
    assert "2+3" in b.fen()  # white has delivered one check


def test_three_check_third_check_wins():
    b = Board("4k3/8/8/8/8/8/8/4KQ2 w - - 1+3 0 1", Variant.THREE_CHECK)
    b.push_uci("f1b5")  # third check by white
    assert b.outcome() == Board.VARIANT_LOSS  # black to move, lost


# -- king of the hill ------------------------------------------------------


def test_koth_center_wins():
    b = Board("4k3/8/8/8/8/4K3/8/8 w - - 0 1", Variant.KING_OF_THE_HILL)
    b.push_uci("e3e4")
    assert b.outcome() == Board.VARIANT_LOSS  # black: enemy king on the hill


# -- batched variant searches through the service --------------------------

pytestmark_async = pytest.mark.anyio


@pytest.fixture(scope="module")
def service():
    svc = SearchService(
        weights=NnueWeights.random(seed=5),
        pool_slots=16,
        batch_capacity=64,
        tt_bytes=8 << 20,
        backend="scalar",
    )
    yield svc
    svc.close()


@pytest.mark.anyio
async def test_service_atomic_winning_capture(service):
    res = await service.search(
        "3k4/3q4/8/8/8/8/8/3QK3 w - - 0 1", [], depth=4, variant=Variant.ATOMIC
    )
    assert res.best_move == "d1d7"
    final = [l for l in res.lines if l.multipv == 1][-1]
    assert final.is_mate and final.value == 1


@pytest.mark.anyio
async def test_service_antichess_forced_capture(service):
    res = await service.search(
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w - - 0 1",
        ["e2e3", "b7b5"],
        depth=4,
        variant=Variant.ANTICHESS,
    )
    assert res.best_move == "f1b5"


@pytest.mark.anyio
async def test_service_three_check_finds_checking_move(service):
    res = await service.search(
        "4k3/8/8/8/8/8/8/4KQ2 w - - 1+3 0 1", [], depth=4,
        variant=Variant.THREE_CHECK,
    )
    final = [l for l in res.lines if l.multipv == 1][-1]
    assert final.is_mate and final.value == 1  # third check = mate score


@pytest.mark.anyio
async def test_service_koth_walks_to_center(service):
    res = await service.search(
        "4k3/8/8/8/8/4K3/8/8 w - - 0 1", [], depth=4,
        variant=Variant.KING_OF_THE_HILL,
    )
    assert res.best_move in {"e3e4", "e3d4"}
    final = [l for l in res.lines if l.multipv == 1][-1]
    assert final.is_mate and final.value == 1


@pytest.mark.anyio
async def test_service_variant_and_standard_concurrently(service):
    import asyncio

    standard = service.search("6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], depth=4)
    variant = service.search(
        "4k3/8/8/8/8/4K3/8/8 w - - 0 1", [], depth=4,
        variant=Variant.KING_OF_THE_HILL,
    )
    res_std, res_koth = await asyncio.gather(standard, variant)
    assert res_std.best_move == "d1d8"
    assert res_koth.best_move in {"e3e4", "e3d4"}
