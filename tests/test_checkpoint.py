"""Checkpoint/resume: a restored run continues bit-exactly, including on
a sharded mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fishnet_tpu.models.az import AzConfig
from fishnet_tpu.train import AzTrainer, NetConfig, Trainer
from fishnet_tpu.train.checkpoint import restore_checkpoint, save_checkpoint

TINY_NNUE = NetConfig(num_features=512, max_active=8, l1=64, l2=15, l3=32)
TINY_AZ = AzConfig(channels=16, blocks=2, value_hidden=16)


def nnue_batch(rng, cfg, batch):
    indices = np.full((batch, 2, cfg.max_active), cfg.num_features, np.int32)
    for b in range(batch):
        for p in range(2):
            indices[b, p, :4] = rng.choice(cfg.num_features, 4, replace=False)
    return {
        "indices": jnp.asarray(indices),
        "buckets": jnp.asarray(rng.integers(0, 8, batch).astype(np.int32)),
        "score_cp": jnp.asarray(rng.normal(0, 100, batch).astype(np.float32)),
        "outcome": jnp.asarray(rng.choice([0.0, 0.5, 1.0], batch).astype(np.float32)),
    }


def test_nnue_resume_bit_exact(tmp_path):
    rng = np.random.default_rng(0)
    trainer = Trainer(cfg=TINY_NNUE)
    batch = nnue_batch(rng, TINY_NNUE, 8)

    # Uninterrupted: 4 steps.
    state = trainer.init(seed=0)
    for _ in range(4):
        state, _ = trainer.step(state, batch)
    reference = jax.device_get(state.params)

    # Interrupted: 2 steps, checkpoint, restore, 2 more.
    state = trainer.init(seed=0)
    for _ in range(2):
        state, _ = trainer.step(state, batch)
    save_checkpoint(tmp_path / "ckpt", state)
    restored = restore_checkpoint(tmp_path / "ckpt", trainer.init(seed=0))
    assert int(restored.step) == 2
    for _ in range(2):
        restored, _ = trainer.step(restored, batch)

    resumed = jax.device_get(restored.params)
    for k in reference:
        np.testing.assert_array_equal(reference[k], resumed[k], err_msg=k)


def test_az_sharded_resume(tmp_path):
    from fishnet_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(devices[:8])
    data, model = mesh.devices.shape
    cfg = AzConfig(channels=8 * model, blocks=2, value_hidden=16)
    trainer = AzTrainer(cfg=cfg, mesh=mesh)

    from test_az_trainer import make_batch

    batch = make_batch(np.random.default_rng(3), 8 * data)
    state = trainer.init(seed=3)
    state, _ = trainer.step(state, batch)
    save_checkpoint(tmp_path / "az", state)
    restored = restore_checkpoint(tmp_path / "az", trainer.init(seed=3))
    assert int(restored.step) == 1
    restored, metrics = trainer.step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(restored.step) == 2
