"""AZ policy+value trainer: loss decreases, sharded step on the virtual
mesh, and checkpoint export round-trips into the az-mcts engine."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fishnet_tpu.models.az import AzConfig
from fishnet_tpu.models.az_encoding import INPUT_PLANES, POLICY_SIZE
from fishnet_tpu.train import AzTrainer

TINY = AzConfig(channels=16, blocks=2, value_hidden=16)


def make_batch(rng, batch):
    planes = rng.normal(0, 1, (batch, 8, 8, INPUT_PLANES)).astype(np.float32)
    pol = np.zeros((batch, POLICY_SIZE), np.float32)
    # Concentrated targets on a few "legal" moves per position.
    for b in range(batch):
        idx = rng.choice(POLICY_SIZE, size=8, replace=False)
        w = rng.random(8).astype(np.float32)
        pol[b, idx] = w / w.sum()
    values = rng.uniform(-1, 1, batch).astype(np.float32)
    return {
        "planes": jnp.asarray(planes),
        "policy_target": jnp.asarray(pol),
        "value_target": jnp.asarray(values),
    }


def test_az_training_overfits_small_batch():
    rng = np.random.default_rng(0)
    trainer = AzTrainer(cfg=TINY, learning_rate=3e-3)
    state = trainer.init(seed=0)
    batch = make_batch(rng, 8)
    losses = []
    for _ in range(30):
        state, metrics = trainer.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses[::10]
    assert int(state.step) == 30


def test_az_training_sharded_mesh():
    from fishnet_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(devices[:8])
    data, model = mesh.devices.shape
    cfg = AzConfig(channels=8 * model, blocks=2, value_hidden=16)
    trainer = AzTrainer(cfg=cfg, mesh=mesh)
    state = trainer.init(seed=1)
    batch = make_batch(np.random.default_rng(1), 8 * data)
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1


def test_az_export_roundtrip_into_engine(tmp_path):
    trainer = AzTrainer(cfg=TINY)
    state = trainer.init(seed=2)
    path = tmp_path / "az.npz"
    trainer.export(state, str(path))

    loaded = np.load(path)
    params = {k: jnp.asarray(loaded[k]) for k in loaded.files}
    assert set(params) == set(state.params)

    # The exported checkpoint must drive the MCTS pool directly.
    from fishnet_tpu.search.mcts import MctsConfig, MctsPool

    pool = MctsPool(params, MctsConfig(batch_capacity=64, az=TINY))
    sid = pool.submit(
        "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], visits=200
    )
    for _ in range(5000):
        pool.step()
        if pool.active() == 0:
            break
    assert pool.harvest(sid).best_move == "d1d8"


def test_az_config_recovered_from_checkpoint_shapes(tmp_path):
    """--az-net-file must work for nets trained with any AzConfig: the
    architecture is inferred from parameter shapes (models/az.py), not
    assumed to be the default."""
    from fishnet_tpu.models.az import az_config_from_params

    cfg = AzConfig(channels=24, blocks=3, value_hidden=20)
    trainer = AzTrainer(cfg=cfg)
    state = trainer.init(seed=3)
    path = tmp_path / "az24.npz"
    trainer.export(state, str(path))

    loaded = np.load(path)
    params = {k: loaded[k] for k in loaded.files}
    assert az_config_from_params(params) == cfg


def test_az_config_rejects_non_az_checkpoint():
    from fishnet_tpu.models.az import az_config_from_params

    with pytest.raises(ValueError, match="not an AZ checkpoint"):
        az_config_from_params({"w": np.zeros((3, 3))})

    # Right keys, tampered shape: still a clear error.
    trainer = AzTrainer(cfg=TINY)
    params = {k: np.asarray(v) for k, v in trainer.init(seed=0).params.items()}
    params["value_fc1_w"] = params["value_fc1_w"][:, :-1]
    with pytest.raises(ValueError, match="does not match"):
        az_config_from_params(params)
