"""Fleet-scale crash tolerance (doc/resilience.md "Fleet chaos"):
fleet fault-site parsing, the chaos proxy's deterministic injection
(502s, latency, partition windows), the liveness/readiness split under
graceful drain, a real client process SIGTERM-drained to exit 0, and
the full fleet smoke — kills, a drain, a partition, restart under
budget, the server-side fleet ledger exactly-once, and the fleet
metric families on /metrics. ``make cluster-smoke`` runs the
``smoke or drain`` subset of this file."""

import asyncio
import json
import os
import signal
import socket
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import aiohttp
import pytest

sys.path.insert(0, str(Path(__file__).parent))
from fake_server import FakeLichess, FakeServer  # noqa: E402

from fishnet_tpu.cluster.proxy import ChaosProxy
from fishnet_tpu.cluster.supervisor import FleetSupervisor, ProcSpec
from fishnet_tpu.resilience import drain
from fishnet_tpu.resilience.faults import FaultPlan, FaultPlanError

pytestmark = pytest.mark.anyio

_REPO_ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# Fleet fault sites
# ---------------------------------------------------------------------------


def test_fleet_sites_parse_and_poll_deterministically():
    plan = FaultPlan.parse(
        "seed=5;proxy.partition:nth=2:latency=1.5;proxy.error5xx:every=3:error;"
        "proc.kill:nth=4:crash;proc.sigterm:nth=6:error"
    )
    # proxy.partition fires exactly on its 2nd poll, with the window arg.
    assert plan.poll("proxy.partition") is None
    rule = plan.poll("proxy.partition")
    assert rule is not None and rule.action == "latency" and rule.arg == 1.5
    assert plan.poll("proxy.partition") is None
    # every=3 on its own independent count.
    assert plan.poll("proxy.error5xx") is None
    assert plan.poll("proxy.error5xx") is None
    assert plan.poll("proxy.error5xx") is not None
    # proc sites: nth = that process's Nth supervisor tick.
    assert [plan.poll("proc.kill") for _ in range(3)] == [None] * 3
    assert plan.poll("proc.kill").action == "crash"
    counts = plan.counts()
    assert counts["proc.kill"] == 4 and counts["proxy.partition"] == 3


def test_unknown_fleet_site_rejected():
    with pytest.raises(FaultPlanError):
        FaultPlan.parse("proxy.meteor:nth=1:error")


# ---------------------------------------------------------------------------
# Chaos proxy
# ---------------------------------------------------------------------------


async def test_chaos_proxy_quiet_is_faithful():
    """With no plan the proxy is pure plumbing: same statuses, same
    bodies, nothing counted but forwards."""
    async with FakeServer() as server:
        proxy = await ChaosProxy(server.endpoint).start()
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{proxy.endpoint}/status") as r:
                    via_proxy = (r.status, await r.json())
                async with session.get(f"{server.endpoint}/status") as r:
                    direct = (r.status, await r.json())
                assert via_proxy == direct
                # An unknown path's 404 passes through too.
                async with session.get(f"{proxy.endpoint}/nope") as r:
                    assert r.status == 404
            assert proxy.stats()["forwarded"] == 2
            assert proxy.stats()["dropped"] == 0
        finally:
            await proxy.close()


async def test_chaos_proxy_injects_502_and_latency_on_schedule():
    # Site counters are polled in order (partition, error5xx, latency)
    # and a firing site short-circuits the rest — so the latency site
    # first sees the SECOND request, and nth=1 delays exactly that one.
    plan = FaultPlan.parse(
        "proxy.error5xx:nth=1:error;proxy.latency:nth=1:latency=0.3"
    )
    async with FakeServer() as server:
        proxy = await ChaosProxy(server.endpoint, plan=plan).start()
        try:
            async with aiohttp.ClientSession() as session:
                url = f"{proxy.endpoint}/status"
                async with session.get(url) as r:
                    assert r.status == 502  # injected, never hit the server
                t0 = time.monotonic()
                async with session.get(url) as r:
                    assert r.status == 200
                assert time.monotonic() - t0 >= 0.3
                async with session.get(url) as r:  # 3rd: clean
                    assert r.status == 200
            stats = proxy.stats()
            assert stats["injected_5xx"] == 1
            assert stats["delayed"] == 1
            assert stats["forwarded"] == 2
        finally:
            await proxy.close()


async def test_chaos_proxy_partition_window_drops_every_request():
    """`proxy.partition:...:latency=S` = connection resets (no HTTP
    response) for the whole S-second window, then traffic resumes."""
    plan = FaultPlan.parse("proxy.partition:nth=2:latency=0.6")
    async with FakeServer() as server:
        proxy = await ChaosProxy(server.endpoint, plan=plan).start()
        try:
            async with aiohttp.ClientSession() as session:
                url = f"{proxy.endpoint}/status"
                async with session.get(url) as r:
                    assert r.status == 200  # poll 1: no rule
                t0 = time.monotonic()
                for _ in range(3):  # window open: every request dies raw
                    with pytest.raises(aiohttp.ClientError):
                        async with session.get(url):
                            pass
                await asyncio.sleep(max(0.0, 0.7 - (time.monotonic() - t0)))
                async with session.get(url) as r:  # window passed
                    assert r.status == 200
            stats = proxy.stats()
            assert stats["partitions"] == 1
            # Connection-level counter: aiohttp retries once on a
            # reused-connection disconnect, so each logical request is
            # dropped at least once, possibly twice.
            assert stats["dropped"] >= 3
            assert stats["forwarded"] == 2
        finally:
            await proxy.close()


# ---------------------------------------------------------------------------
# Liveness/readiness split under drain (in-process)
# ---------------------------------------------------------------------------


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=5) as res:
            return res.status, res.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def test_drain_flips_readiness_not_liveness():
    from fishnet_tpu import telemetry

    exporter = telemetry.start_exporter(0)
    try:
        base = f"http://127.0.0.1:{exporter.port}"
        # Before drain: both probes 200, and the readiness body is the
        # pre-drain bare "ok" (no provider registered yet — the
        # single-process behavior is byte-for-byte unchanged).
        assert _get(f"{base}/healthz") == (200, b"ok\n")
        assert _get(f"{base}/healthz/ready") == (200, b"ok\n")
        assert _get(f"{base}/healthz/live") == (200, b"ok\n")

        assert drain.begin(
            "sigterm", deadline=25.0, depth_fn=lambda: {"batches": 2}
        ) is True
        assert drain.begin("sigterm") is False  # idempotent

        status, body = _get(f"{base}/healthz")
        assert status == 503
        payload = json.loads(body)["providers"]["drain"]
        assert payload["draining"] is True
        assert payload["reason"] == "sigterm"
        assert payload["pending"] == {"batches": 2}
        assert _get(f"{base}/healthz/ready")[0] == 503
        # Liveness NEVER couples to drain: the process is flushing,
        # not wedged — an orchestrator must not kill it mid-drain.
        assert _get(f"{base}/healthz/live") == (200, b"ok\n")

        metrics = _get(f"{base}/metrics")[1].decode()
        assert "fishnet_drain_state 1" in metrics

        drain.reset()
        assert _get(f"{base}/healthz") == (200, b"ok\n")
        assert "fishnet_drain_state 0" in _get(f"{base}/metrics")[1].decode()
    finally:
        drain.reset()
        exporter.close()
        telemetry.disable()


# ---------------------------------------------------------------------------
# Real process: SIGTERM drain
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def test_sigterm_drains_real_process_to_exit_zero(tmp_path):
    """The whole drain contract against a REAL `python -m fishnet_tpu`
    process: on SIGTERM it goes 503 on readiness (while liveness stays
    200), flushes in-flight work within the deadline, and exits 0 —
    with the server-side fleet ledger clean afterwards. A submit
    latency fault stretches the flush window so the draining state is
    reliably observable from outside."""
    metrics_port = _free_port()
    lichess = FakeLichess(require_key=False)
    lichess.auto_refill = 4
    lichess.refill_move_every = 4
    async with FakeServer(lichess) as server:
        env = dict(os.environ)
        env["PYTHONPATH"] = str(_REPO_ROOT)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log_path = tmp_path / "client.log"
        logf = open(log_path, "ab")
        try:
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "fishnet_tpu", "run",
                "--no-conf", "--no-stats-file", "--engine", "mock",
                "--endpoint", server.endpoint, "--key", "DRAINPROC",
                "--cores", "1", "--max-backoff", "1s",
                "--drain-deadline", "10s",
                "--metrics-port", str(metrics_port),
                "--fault-plan", "net.submit:every=1:latency=0.5",
                stdout=logf, stderr=asyncio.subprocess.STDOUT,
                cwd=str(tmp_path), env=env,
            )
        finally:
            logf.close()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if lichess.acquire_count > 0 and lichess.fleet.units:
                    break
                await asyncio.sleep(0.05)
            assert lichess.acquire_count > 0, log_path.read_text()

            proc.send_signal(signal.SIGTERM)
            saw_unready = saw_alive = False
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not saw_unready:
                try:
                    status, body = _get(
                        f"http://127.0.0.1:{metrics_port}/healthz"
                    )
                    if status == 503 and b"draining" in body:
                        saw_unready = True
                        saw_alive = _get(
                            f"http://127.0.0.1:{metrics_port}/healthz/live"
                        ) == (200, b"ok\n")
                except OSError:
                    pass  # exporter may already be gone — checked below
                await asyncio.sleep(0.05)

            rc = await asyncio.wait_for(proc.wait(), 30)
            assert rc == 0, f"drain exited {rc}: {log_path.read_text()}"
            assert saw_unready, "readiness never went 503 during drain"
            assert saw_alive, "liveness failed during drain"
        finally:
            if proc.returncode is None:
                proc.kill()
                await proc.wait()
        # Server-side audit: everything handed to the drained process
        # either completed or is back in the queue — nothing lost.
        report = lichess.fleet_report()
        assert report["clean"], report


# ---------------------------------------------------------------------------
# Fleet smoke: kills + drain + partition, exactly-once, metric families
# ---------------------------------------------------------------------------


async def test_sigkill_reassignment_fleet_smoke():
    """kill -9 mid-dispatch on one process of a two-process fleet: the
    server's reassignment sweep hands its work out again, the
    supervisor restarts it under budget, and the fleet ledger ends
    exactly-once — 0 lost, 0 duplicated."""
    lichess = FakeLichess(require_key=False)
    lichess.auto_refill = 4
    lichess.refill_move_every = 4
    lichess.reassign_after = 1.5
    async with FakeServer(lichess) as server:
        supervisor = FleetSupervisor(
            server.endpoint,
            [
                ProcSpec(name="KA", fault_spec="proc.kill:nth=10:crash"),
                ProcSpec(name="KB"),
            ],
            tick_seconds=0.2,
            drain_deadline=5.0,
        )
        await supervisor.start()
        try:
            t0 = time.monotonic()
            while time.monotonic() - t0 < 9.0:
                await asyncio.sleep(0.25)
            exit_codes = await supervisor.drain()
        except BaseException:
            await supervisor.kill_all()
            raise
    kinds = [k for _, _, k in supervisor.events]
    assert "kill" in kinds, kinds
    assert "restart" in kinds, kinds
    assert supervisor.procs["KA"].exit_codes[0] == -signal.SIGKILL
    assert exit_codes == {"KA": 0, "KB": 0}
    report = lichess.fleet_report()
    assert report["clean"], report
    assert report["completed"] > 0
    assert report["reassigned"] >= 1, report


async def test_cluster_chaos_smoke_end_to_end():
    """The canned fleet scenario (SIGKILL + SIGTERM drain + partition
    across 3 real processes) via the chaos harness: ledger clean,
    restart under budget, every drained process exits 0, and the fleet
    metric families exported on /metrics."""
    from fishnet_tpu.cluster.chaos import run_chaos

    report = await run_chaos(procs=3, seconds=8.0, drain_deadline=5.0)
    assert report["ok"] is True
    kinds = [k for _, _, k in report["events"]]
    assert "kill" in kinds
    assert "sigterm" in kinds
    assert sum(p["partitions"] for p in report["proxies"].values()) >= 1
    assert report["fleet"]["clean"]
    assert report["fleet"]["lost"] == [] and report["fleet"]["duplicated"] == []
    assert all(rc == 0 for rc in report["exit_codes"].values())
    assert report["metric_families"] == sorted(
        [
            "fishnet_proc_restarts_total",
            "fishnet_fleet_partitions_total",
            "fishnet_faults_injected_total",
        ]
    )
