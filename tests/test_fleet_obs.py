"""Fleet observability plane (doc/observability.md "Fleet
observability"): histogram quantile summaries, build-info families,
the exporter scrape-vs-shutdown race, cross-process trace stitching
(reassignment joins, fenced late submits, zero orphans), the SLO
burn-rate engine, and the FleetAggregator's federation + staleness
semantics. ``make fleet-obs-smoke`` additionally runs the ``slow``
tests here: real supervised processes under a SIGKILL with the
aggregator scraping throughout."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))

from fishnet_tpu.telemetry import registry as reg
from fishnet_tpu.telemetry.critical_path import group_traces, orphan_spans
from fishnet_tpu.telemetry.exporter import MetricsExporter
from fishnet_tpu.telemetry.fleet import FleetAggregator, port_dir_targets
from fishnet_tpu.telemetry.registry import (
    MetricFamily,
    MetricsRegistry,
    Sample,
    histogram_quantiles,
    percentile,
    quantile_from_buckets,
)
from fishnet_tpu.telemetry.slo import SLO, Selector, SLOEngine, default_slos
from fishnet_tpu.telemetry.stitch import (
    attribute_fleet_trace,
    fleet_report,
    is_global_trace_id,
    stitch,
    tag_actor_spans,
)
from fishnet_tpu.telemetry.trace_export import (
    chrome_trace,
    validate_chrome_trace,
)
from fishnet_tpu.telemetry.tracing import trace_id_for_batch

_REPO_ROOT = Path(__file__).resolve().parents[1]


def _get(url: str, timeout: float = 3.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        assert resp.status == 200
        return resp.read()


# ---------------------------------------------------------------------------
# Quantile summaries (registry.py)
# ---------------------------------------------------------------------------


def test_percentile_shared_definition():
    assert percentile([], 99) is None
    assert percentile([5.0], 50) == 5.0
    vals = list(range(1, 101))
    # Nearest-rank over (n-1)-scaled index: see registry.percentile.
    assert percentile(vals, 50) == 51
    assert percentile(vals, 99) == 99
    # bench.py delegates to this definition.
    import bench

    assert bench._percentile([1.0, 2.0, 3.0], 50) == 2.0


def test_quantile_from_buckets_interpolates_and_clamps():
    bounds = [0.1, 1.0, 10.0]
    # 10 obs <= 0.1, 10 more in (0.1, 1.0], none beyond.
    assert quantile_from_buckets(bounds, [10, 20, 20], 20, 0.5) == 0.1
    mid = quantile_from_buckets(bounds, [10, 20, 20], 20, 0.75)
    assert 0.1 < mid <= 1.0
    # Observations past the last finite bound clamp to it.
    assert quantile_from_buckets(bounds, [0, 0, 0], 5, 0.99) == 10.0
    assert quantile_from_buckets(bounds, [], 0, 0.5) is None


def test_render_json_carries_histogram_quantiles():
    registry = MetricsRegistry()
    hist = registry.histogram(
        "test_fleet_seconds", "h", buckets=(0.1, 1.0, 10.0),
        labelnames=("endpoint",),
    )
    for _ in range(10):
        hist.observe(0.05, endpoint="a")
    for _ in range(10):
        hist.observe(5.0, endpoint="a")
    doc = registry.render_json()
    entry = doc["metrics"]["test_fleet_seconds"]
    rows = {
        r["labels"]["endpoint"]: r for r in entry["quantiles"]
    }
    assert rows["a"]["count"] == 20
    assert rows["a"]["p50"] <= 1.0 < rows["a"]["p99"] <= 10.0
    # Families without observations expose no quantile rows.
    fam = MetricFamily("empty_seconds", "histogram", "h")
    assert histogram_quantiles(fam) == []


# ---------------------------------------------------------------------------
# Build info + start time (exporter.py)
# ---------------------------------------------------------------------------


def test_every_exporter_serves_build_info_and_start_time():
    registry = MetricsRegistry()
    exporter = MetricsExporter(port=0, registry=registry)
    try:
        text = _get(exporter.url + "/metrics").decode()
    finally:
        exporter.close()
    assert "# TYPE fishnet_build_info gauge" in text
    assert 'fishnet_build_info{' in text
    for label in ("version=", "abi=", "jax="):
        assert label in text
    assert "fishnet_proc_start_time_seconds" in text
    start = [
        line for line in text.splitlines()
        if line.startswith("fishnet_proc_start_time_seconds")
    ][0]
    assert 0 < float(start.split()[-1]) <= time.time()


def test_exporter_close_refuses_scrapes_instead_of_racing():
    registry = MetricsRegistry()
    exporter = MetricsExporter(port=0, registry=registry)
    url = exporter.url
    errors = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                _get(url + "/metrics", timeout=1.0)
            except AssertionError:
                pass  # 503 while closing: the refusal path
            except Exception as exc:  # noqa: BLE001
                if not isinstance(exc, (OSError, urllib.error.URLError)):
                    errors.append(exc)
                return

    import urllib.error

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    exporter.close()  # must not deadlock against in-flight scrapes
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert not errors
    # close() drained the registry's scrape path too.
    registry.scrape_barrier()


# ---------------------------------------------------------------------------
# Cross-process trace stitching (stitch.py)
# ---------------------------------------------------------------------------


def _span(stage, t, dur_ms, tid=None, sid=None, parent=None, **fields):
    s = {"stage": stage, "t": t, "dur_ms": dur_ms, "thread": "w0"}
    if tid is not None:
        s["trace_id"] = tid
    if sid is not None:
        s["span_id"] = sid
    if parent is not None:
        s["parent_id"] = parent
    s.update(fields)
    return s


def test_global_trace_id_is_the_batch_digest_shape():
    tid = trace_id_for_batch("workunit-1")
    assert is_global_trace_id(tid)
    assert not is_global_trace_id("3.7")  # step trace: tid.counter
    assert not is_global_trace_id("ABCDEF0123456789")  # uppercase


def test_tag_actor_spans_namespaces_and_rebases():
    tid = trace_id_for_batch("B")
    spans = [
        _span("acquire", 1.0, 100.0, tid=tid, sid=tid),
        _span("pack", 2.0, 5.0, tid="3.7", sid="3.8", parent="3.7",
              links=[["3.7", "3.9"]]),
    ]
    out = tag_actor_spans("A@1", "PROC0", spans, epoch_offset=1000.0)
    assert out[0]["t"] == 1001.0 and out[0]["proc"] == "PROC0"
    assert out[0]["trace_id"] == tid  # global: the join key survives
    assert out[0]["span_id"] == f"A@1/{tid}"
    assert out[1]["trace_id"] == "A@1/3.7"  # step trace: namespaced
    assert out[1]["links"] == [["A@1/3.7", "A@1/3.9"]]
    assert spans[0]["t"] == 1.0  # inputs untouched


def _two_proc_dump(fenced_submit=False):
    """Synthetic two-process span dumps for one reassigned work unit:
    PROC0 acquires and dies; PROC1 re-acquires after the server's
    reassignment sweep and completes. With ``fenced_submit`` PROC0
    also submits late (partition, not death) and is fenced."""
    tid = trace_id_for_batch("game42")
    a = [
        _span("acquire", 10.0, 50.0, tid=tid, sid=tid),
        _span("schedule", 10.1, 5.0, tid=tid, sid="1.1", parent=tid),
        _span("queue_wait", 10.15, 200.0, tid=tid, sid="1.2", parent="1.1"),
    ]
    if fenced_submit:
        a.append(
            _span("submit", 13.5, 40.0, tid=tid, sid="1.3", parent=tid)
        )
    b = [
        _span("acquire", 12.5, 60.0, tid=tid, sid=tid),
        _span("schedule", 12.6, 4.0, tid=tid, sid="2.1", parent=tid),
        _span("queue_wait", 12.65, 150.0, tid=tid, sid="2.2", parent="2.1"),
        _span("submit", 13.0, 30.0, tid=tid, sid="2.3", parent=tid),
    ]
    return tid, [
        {"proc": "PROC0", "actor": "PROC0@100", "spans": a,
         "epoch_offset": 0.0},
        {"proc": "PROC1", "actor": "PROC1@200", "spans": b,
         "epoch_offset": 0.0},
    ]


def test_stitch_joins_reassigned_unit_into_one_tree():
    tid, incs = _two_proc_dump()
    report = stitch(incs)
    assert report["traces"] == 1
    assert report["cross_proc"] == [tid]
    assert report["reassignments"] == 1 and report["fenced"] == 0
    spans = [s for s in report["spans"] if s.get("trace_id") == tid]
    reassign = [s for s in spans if s["stage"] == "reassignment"]
    assert len(reassign) == 1
    r = reassign[0]
    assert r["from_actor"] == "PROC0@100" and r["to_actor"] == "PROC1@200"
    # Explicit link to where the dead actor went dark.
    assert [tid, "PROC0@100/1.2"] in r["links"]
    # The successor's root is parented under the reassignment span,
    # which is parented under the primary root: ONE tree.
    b_root = next(s for s in spans if s["span_id"] == f"PROC1@200/{tid}")
    assert b_root["parent_id"] == r["span_id"]
    assert r["parent_id"] == f"PROC0@100/{tid}"
    roots = [s for s in spans if s.get("parent_id") is None]
    assert len(roots) == 1 and roots[0]["span_id"] == f"PROC0@100/{tid}"
    # Zero orphans through the single-process grouper too.
    for trace in group_traces(report["spans"]).values():
        assert orphan_spans(trace) == []


def test_stitch_marks_fenced_late_submit():
    tid, incs = _two_proc_dump(fenced_submit=True)
    report = stitch(incs)
    assert report["fenced"] == 1
    spans = [s for s in report["spans"] if s.get("trace_id") == tid]
    r = next(s for s in spans if s["stage"] == "reassignment")
    late = next(s for s in spans if s["span_id"] == "PROC0@100/1.3")
    assert late.get("fenced") is True
    assert [tid, "PROC0@100/1.3"] in r["links"]
    assert r["fenced"] is True
    for trace in group_traces(report["spans"]).values():
        assert orphan_spans(trace) == []


def test_stitch_keeps_step_traces_per_process():
    # Identical process-local step trace ids must NOT merge.
    a = [_span("pack", 1.0, 5.0, tid="3.1", sid="3.2", parent="3.1")]
    b = [_span("pack", 1.0, 5.0, tid="3.1", sid="3.2", parent="3.1")]
    report = stitch([
        {"proc": "P0", "actor": "P0@1", "spans": a, "epoch_offset": 0.0},
        {"proc": "P1", "actor": "P1@2", "spans": b, "epoch_offset": 0.0},
    ])
    tids = {s["trace_id"] for s in report["spans"]}
    assert tids == {"P0@1/3.1", "P1@2/3.1"}


def test_fleet_attribution_sums_to_wall_with_reassignment():
    tid, incs = _two_proc_dump()
    report = stitch(incs)
    spans = [s for s in report["spans"] if s.get("trace_id") == tid]
    attr = attribute_fleet_trace(spans)
    total = sum(
        attr[c] for c in (
            "acquire", "schedule", "queue_wait", "compute", "submit",
            "reassignment", "other",
        )
    )
    assert attr["wall_ms"] > 0
    assert abs(total - attr["wall_ms"]) < 1e-6
    assert attr["reassignment"] > 0
    assert attr["coverage"] > 0.9
    # Per-proc attribution names both processes.
    assert set(attr["per_proc"]) == {"PROC0", "PROC1"}

    fleet = fleet_report(report["spans"])
    assert fleet["traces"] == 1
    assert fleet["reassignment_ms"] > 0
    assert set(fleet["per_proc"]) == {"PROC0", "PROC1"}


def test_fleet_chrome_export_one_track_group_per_proc():
    _, incs = _two_proc_dump()
    trace = chrome_trace(stitch(incs)["spans"])
    validate_chrome_trace(trace)
    proc_meta = {
        ev["args"]["name"] for ev in trace["traceEvents"]
        if ev["ph"] == "M" and ev["name"] == "process_name"
    }
    assert proc_meta == {"PROC0", "PROC1"}
    pids = {
        ev["pid"] for ev in trace["traceEvents"] if ev["ph"] == "X"
    }
    assert len(pids) == 2
    # The reassignment link renders as a cross-track flow arrow.
    assert any(ev["ph"] == "s" for ev in trace["traceEvents"])


# ---------------------------------------------------------------------------
# SLO burn-rate engine (slo.py)
# ---------------------------------------------------------------------------


def _counter_fams(total, bad):
    fam = MetricFamily("req_total", "counter", "h")
    fam.samples.append(Sample("req_total", total, {"outcome": "ok"}))
    fam.samples.append(Sample("req_total", bad, {"outcome": "error"}))
    return {"req_total": fam}


def _ratio_slo(objective=0.9):
    return SLO(
        name="t", description="d", objective=objective,
        total=Selector("req_total"),
        bad=Selector("req_total", {"outcome": "error"}),
    )


def test_ratio_slo_burn_rates_multi_window():
    eng = SLOEngine([_ratio_slo(0.9)], windows=(60.0, 300.0))
    t0 = 1000.0
    eng.observe(_counter_fams(100, 0), now=t0)
    # 100 more requests, 20 bad, inside the short window: 20% bad over
    # a 10% budget = burn 2.0 on BOTH windows (same delta).
    eng.observe(_counter_fams(180, 20), now=t0 + 30)
    rows = eng.evaluate(now=t0 + 30)
    assert rows[0]["windows"]["60s"] == pytest.approx(2.0)
    assert rows[0]["status"] == "breach"
    # A later clean minute: the short window calms first.
    eng.observe(_counter_fams(1180, 20), now=t0 + 120)
    rows = eng.evaluate(now=t0 + 120)
    assert rows[0]["windows"]["60s"] == 0.0
    assert rows[0]["windows"]["300s"] > 0.0


def test_slo_no_traffic_is_not_burning():
    eng = SLOEngine([_ratio_slo()], windows=(60.0,))
    eng.observe(_counter_fams(50, 5), now=0.0)
    eng.observe(_counter_fams(50, 5), now=30.0)
    rows = eng.evaluate(now=30.0)
    assert rows[0]["windows"]["60s"] == 0.0
    assert rows[0]["status"] == "ok"


def test_latency_slo_counts_good_from_snapped_bucket():
    fam = MetricFamily("lat_seconds", "histogram", "h")

    def snap(le, v):
        return Sample("lat_seconds_bucket", v, {"le": le})

    def fams(under, total):
        f = MetricFamily("lat_seconds", "histogram", "h")
        f.samples = [
            snap("1", under), snap("2.5", under), snap("+Inf", total),
            Sample("lat_seconds_count", total, {}),
            Sample("lat_seconds_sum", 0.0, {}),
        ]
        return {"lat_seconds": f}

    slo = SLO(
        name="lat", description="d", objective=0.9,
        total=Selector("lat_seconds"), threshold_s=2.0,
    )
    good, total, snapped = slo.good_total(fams(80, 100))
    assert (good, total) == (80.0, 100.0)
    assert snapped == 2.5  # 2.0 snapped up to the 2.5 bound
    eng = SLOEngine([slo], windows=(60.0,))
    eng.observe(fams(80, 100), now=0.0)
    eng.observe(fams(160, 200), now=30.0)  # 20% over-threshold
    rows = eng.evaluate(now=30.0)
    assert rows[0]["windows"]["60s"] == pytest.approx(2.0)
    assert rows[0]["snapped_bound_s"] == 2.5


def test_slo_families_exposition_shape():
    eng = SLOEngine([_ratio_slo()], windows=(60.0,))
    eng.observe(_counter_fams(10, 0), now=0.0)
    fams = {f.name: f for f in eng.families(now=0.0)}
    burn = fams["fishnet_slo_burn_rate"].samples
    assert burn[0].labels == {"slo": "t", "window": "60s"}
    assert fams["fishnet_slo_status"].samples[0].value == 0.0


def test_default_slos_reference_live_family_names():
    names = {s.name for s in default_slos()}
    assert {"move_latency", "analysis_ttfa", "api_success"} <= names
    for slo in default_slos():
        assert slo.total.family.startswith("fishnet_")


# ---------------------------------------------------------------------------
# FleetAggregator federation + staleness
# ---------------------------------------------------------------------------


def _proc_exporter(reqs_ok: int):
    registry = MetricsRegistry()
    counter = registry.counter(
        "fishnet_api_requests_total", "h", labelnames=("endpoint", "outcome")
    )
    for _ in range(reqs_ok):
        counter.inc(endpoint="acquire", outcome="ok")
    return MetricsExporter(port=0, registry=registry)


def test_aggregator_federates_with_proc_labels_and_meta():
    e0, e1 = _proc_exporter(3), _proc_exporter(5)
    agg = FleetAggregator(
        targets={"PROC0": e0.url, "PROC1": e1.url}
    )
    try:
        agg.poll_once()
        fams = agg.federated_families()
        samples = fams["fishnet_api_requests_total"].samples
        by_proc = {
            s.labels["proc"]: s.value for s in samples
            if s.labels.get("endpoint") == "acquire"
        }
        assert by_proc == {"PROC0": 3.0, "PROC1": 5.0}
        ups = {
            s.labels["proc"]: s.value
            for s in fams["fishnet_fleet_proc_up"].samples
        }
        assert ups == {"PROC0": 1.0, "PROC1": 1.0}
        # Build info federates per proc too (satellite 1 contract).
        info = fams["fishnet_build_info"].samples
        assert {s.labels["proc"] for s in info} == {"PROC0", "PROC1"}
        # SLO families ride the same exposition.
        assert "fishnet_slo_burn_rate" in fams
    finally:
        agg.close()
        e0.close()
        e1.close()


def test_aggregator_keeps_dead_proc_series_marked_stale():
    e0, e1 = _proc_exporter(3), _proc_exporter(5)
    agg = FleetAggregator(targets={"PROC0": e0.url, "PROC1": e1.url})
    try:
        agg.poll_once()
        e1.close()  # SIGKILL-shaped: the target stops answering
        agg.poll_once()  # must not raise
        fams = agg.federated_families()
        ups = {
            s.labels["proc"]: s.value
            for s in fams["fishnet_fleet_proc_up"].samples
        }
        assert ups == {"PROC0": 1.0, "PROC1": 0.0}
        # The dead proc's last-known series are STILL exported.
        by_proc = {
            s.labels["proc"]: s.value
            for s in fams["fishnet_api_requests_total"].samples
            if s.labels.get("endpoint") == "acquire"
        }
        assert by_proc["PROC1"] == 5.0
        errs = {
            s.labels["proc"]: s.value
            for s in fams["fishnet_fleet_scrape_errors_total"].samples
        }
        assert errs["PROC1"] >= 1.0
        doc = agg.fleet_doc()
        assert doc["procs"]["PROC1"]["up"] is False
        assert doc["procs"]["PROC1"]["last_error"]
    finally:
        agg.close()
        e0.close()


def test_aggregator_serves_fleet_routes():
    e0 = _proc_exporter(2)
    agg = FleetAggregator(targets={"PROC0": e0.url})
    srv = agg.serve(0)
    try:
        agg.poll_once()
        doc = json.loads(_get(srv.url + "/fleet"))
        assert doc["procs"]["PROC0"]["up"] is True
        slo_doc = json.loads(_get(srv.url + "/fleet/slo"))
        assert {row["slo"] for row in slo_doc["slo"]} == {
            s.name for s in default_slos()
        }
        trace = json.loads(_get(srv.url + "/fleet/trace"))
        validate_chrome_trace(trace)
        # The federated exposition includes the proc-labeled series.
        text = _get(srv.url + "/metrics").decode()
        assert 'proc="PROC0"' in text
        assert "fishnet_fleet_proc_up" in text
        assert "fishnet_slo_burn_rate" in text
    finally:
        agg.close()
        e0.close()


def test_port_dir_discovery_follows_rewrites(tmp_path):
    e0 = _proc_exporter(1)
    (tmp_path / "PROC0.port").write_text(f"{e0.port}\n")
    (tmp_path / "junk.port").write_text("not-a-port\n")
    resolve = port_dir_targets(str(tmp_path))
    assert resolve() == {"PROC0": f"http://127.0.0.1:{e0.port}"}
    agg = FleetAggregator(targets_fn=resolve)
    try:
        agg.poll_once()
        assert agg.fleet_doc()["procs"]["PROC0"]["up"] is True
        # Port file disappears (child died, file cleaned): stale, kept.
        (tmp_path / "PROC0.port").unlink()
        agg.poll_once()
        doc = agg.fleet_doc()
        assert doc["procs"]["PROC0"]["up"] is False
    finally:
        agg.close()
        e0.close()


def test_journal_recovers_spans_lost_to_sigkill(tmp_path):
    """The write-ahead journal closes the scrape race: a span recorded
    AFTER the aggregator's last scrape of a process that is then
    SIGKILLed must still reach the stitcher via the journal tail, and
    a span present in BOTH the scrape and the journal must not
    double-count."""
    from fishnet_tpu.telemetry.spans import SpanRecorder
    from fishnet_tpu.telemetry.tracing import batch_root

    journal = tmp_path / "PROC0.journal.jsonl"
    rec = SpanRecorder()
    rec.journal_to(str(journal))
    t0 = time.monotonic()
    rec.record("acquire", t0, trace=batch_root("doomed-unit"), batch="doomed-unit")
    # Step traces stay ring-only: never journaled.
    from fishnet_tpu.telemetry.tracing import new_trace

    rec.record("pack", t0, trace=new_trace())
    rec.journal_close()
    lines = journal.read_text().strip().splitlines()
    header = json.loads(lines[0])
    assert header["format"].startswith("fishnet-spans-journal/")
    assert header["pid"] == os.getpid()
    recs = [json.loads(ln) for ln in lines[1:]]
    assert [r["stage"] for r in recs] == ["acquire"]
    # Journal record is byte-identical in content to the /spans shape,
    # so the incarnation dedup collapses scrape+journal duplicates.
    scraped = [s for s in rec.spans() if s["stage"] == "acquire"]
    assert recs[0] == scraped[0]

    agg = FleetAggregator(targets={}, journal_dir=str(tmp_path))
    try:
        agg.poll_once()
        st = agg.stitched()
        acq = [s for s in st["spans"] if s["stage"] == "acquire"]
        assert len(acq) == 1
        assert acq[0]["proc"] == "PROC0"
        assert acq[0]["actor"] == f"PROC0@{os.getpid()}"
        doc = agg.fleet_doc()
        # Journal-only proc: known (archived), never scraped, not up.
        assert doc["procs"]["PROC0"]["up"] is False
    finally:
        agg.close()


# ---------------------------------------------------------------------------
# Churn + supervised fleet (slow; `make fleet-obs-smoke`)
# ---------------------------------------------------------------------------

_CHILD = """
import sys, time
from fishnet_tpu import telemetry
exporter = telemetry.start_exporter(0)
with open(sys.argv[1] + ".tmp", "w") as fp:
    fp.write(str(exporter.port))
import os
os.replace(sys.argv[1] + ".tmp", sys.argv[1])
time.sleep(120)
"""


@pytest.mark.slow
def test_scrape_loop_survives_sigkill_restart_churn(tmp_path):
    """Satellite 3 regression: the aggregator polls in a tight loop
    while a real exporter process is SIGKILLed and restarted 10x. The
    aggregator must never crash, must flip up/stale each death, and
    must key a fresh incarnation per pid."""
    port_file = tmp_path / "CHURN.port"
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{_REPO_ROOT}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(_REPO_ROOT)
    )
    env.setdefault("JAX_PLATFORMS", "cpu")

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(port_file)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    agg = FleetAggregator(
        targets_fn=port_dir_targets(str(tmp_path)), poll_interval=0.05
    ).start()
    pids = []
    try:
        for _ in range(10):
            child = spawn()
            pids.append(child.pid)
            deadline = time.time() + 20
            while not port_file.exists() and time.time() < deadline:
                time.sleep(0.05)
            assert port_file.exists(), "child never wrote its port file"
            time.sleep(0.3)  # let a few scrapes land
            os.kill(child.pid, signal.SIGKILL)
            child.wait(timeout=10)
            port_file.unlink(missing_ok=True)
            time.sleep(0.15)
        # Aggregator thread is alive and the state is coherent.
        doc = agg.fleet_doc()
        st = doc["procs"]["CHURN"]
        assert st["up"] is False
        assert st["scrapes"] >= 5
        # Each restart was a distinct incarnation (distinct pid).
        assert len(st["pids"]) >= 5
        assert set(st["pids"]) <= set(pids)
    finally:
        agg.close()


@pytest.mark.slow
@pytest.mark.anyio
async def test_supervised_fleet_observed_through_a_kill(tmp_path):
    """The tentpole end-to-end: 3 supervised client processes with one
    SIGKILL mid-run; the fleet aggregator (discovering via the
    supervisor's port files) must federate all 3 procs, mark the
    killed one stale while it is down, archive enough spans to stitch,
    evaluate SLOs from federated series, and export a valid fleet
    Perfetto trace."""
    from fake_server import FakeLichess, FakeServer

    from fishnet_tpu.cluster.supervisor import FleetSupervisor, ProcSpec

    lichess = FakeLichess(require_key=False)
    lichess.auto_refill = 6
    lichess.refill_move_every = 4
    lichess.reassign_after = 1.5
    specs = [
        ProcSpec(name="PROC0", fault_spec="seed=3;proc.kill:nth=10:crash"),
        ProcSpec(name="PROC1"),
        ProcSpec(name="PROC2"),
    ]
    stale_seen = False
    async with FakeServer(lichess) as server:
        supervisor = FleetSupervisor(
            server.endpoint,
            specs,
            workdir=str(tmp_path),
            tick_seconds=0.2,
            drain_deadline=4.0,
        )
        await supervisor.start()
        agg = FleetAggregator(
            targets_fn=port_dir_targets(str(tmp_path)),
            poll_interval=0.25,
            journal_dir=str(tmp_path),
        ).start()
        try:
            import asyncio

            t0 = time.monotonic()
            while time.monotonic() - t0 < 14.0:
                await asyncio.sleep(0.25)
                kinds = [k for _, _, k in supervisor.events]
                if "kill" in kinds and not stale_seen:
                    # Probe the live aggregator state during the stale
                    # window (before the supervisor respawns).
                    doc = agg.fleet_doc()
                    downs = [
                        n for n, st in doc["procs"].items() if not st["up"]
                    ]
                    if "PROC0" in downs:
                        fams = agg.federated_families()
                        procs_in_series = {
                            s.labels.get("proc")
                            for s in fams[
                                "fishnet_api_requests_total"
                            ].samples
                        }
                        assert "PROC0" in procs_in_series
                        stale_seen = True
                if stale_seen and "restart" in kinds and (
                    time.monotonic() - t0 > 8.0
                ):
                    break
            agg.poll_once()
            doc = agg.fleet_doc()
        finally:
            agg.close()
            await supervisor.kill_all()

    assert stale_seen, "never observed PROC0 stale during its kill window"
    assert set(doc["procs"]) == {"PROC0", "PROC1", "PROC2"}
    assert all(st["scrapes"] >= 1 for st in doc["procs"].values())
    # The killed proc restarted under a fresh pid: >= 2 incarnations.
    assert len(doc["procs"]["PROC0"]["pids"]) >= 2
    assert doc["stitch"]["traces"] >= 1
    assert doc["slo"], "SLO evaluation missing"
    assert doc["critical_path"]["traces"] >= 1


# ---------------------------------------------------------------------------
# Shared-plane MCTS families federate (doc/search.md)
# ---------------------------------------------------------------------------


def test_mcts_tree_families_federate_with_proc_labels():
    """The MCTS tree-side families ride the standard exposition: a proc
    that ran an MctsPool federates them through the FleetAggregator
    with proc labels intact, next to every other family."""
    import numpy as np

    from fishnet_tpu.models.az_encoding import POLICY_SIZE
    from fishnet_tpu.search.mcts import MctsConfig, MctsPool

    class _InstantEval:
        def warmup(self, cap):
            pass

        def evaluate(self, planes_u8, n, keys=None):
            return (
                np.zeros((n, POLICY_SIZE), np.float32),
                np.zeros(n, np.float32),
            )

        def close(self):
            pass

    start = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    pool = MctsPool(
        {}, MctsConfig(batch_capacity=32), evaluator=_InstantEval()
    )
    sids = [pool.submit(start, [], 20) for _ in range(2)]
    while pool.active() > 0:
        pool.step()
    for sid in sids:
        pool.harvest(sid)
    pool.close()

    exporter = MetricsExporter(port=0, registry=reg.REGISTRY)
    agg = FleetAggregator(targets={"PROC0": exporter.url})
    try:
        agg.poll_once()
        fams = agg.federated_families()
        for name in (
            "fishnet_mcts_visits_total",
            "fishnet_mcts_collisions_total",
            "fishnet_mcts_subtree_reuse_total",
            "fishnet_mcts_batch_fill_ratio",
            "fishnet_mcts_trees_active",
        ):
            assert name in fams, name
            assert fams[name].samples
            assert all(
                s.labels.get("proc") == "PROC0" for s in fams[name].samples
            )
        visits = sum(
            s.value for s in fams["fishnet_mcts_visits_total"].samples
        )
        assert visits >= 40
    finally:
        agg.close()
        exporter.close()


# ---------------------------------------------------------------------------
# Journal robustness + the --profiles console panel (ISSUE 15)
# ---------------------------------------------------------------------------


def test_journal_tolerates_torn_partial_tail(tmp_path):
    """A crash mid-write leaves a newline-less torn tail: the reader
    must consume only complete lines, leave the cursor before the torn
    one, and — once the line is completed — deliver that span exactly
    once on the next poll."""
    from fishnet_tpu.telemetry.spans import SpanRecorder
    from fishnet_tpu.telemetry.tracing import batch_root

    journal = tmp_path / "PROC0.journal.jsonl"
    rec = SpanRecorder()
    rec.journal_to(str(journal))
    rec.record(
        "acquire", time.monotonic(), trace=batch_root("unit-a"),
        batch="unit-a",
    )
    rec.journal_close()
    full = journal.read_bytes()
    lines = full.splitlines(keepends=True)
    torn = lines[-1]
    journal.write_bytes(b"".join(lines[:-1]) + torn[: len(torn) // 2])

    agg = FleetAggregator(targets={}, journal_dir=str(tmp_path))
    try:
        agg.poll_once()  # must not raise, must not consume the torn tail
        spans = agg.stitched()["spans"]
        assert [s for s in spans if s["stage"] == "acquire"] == []
        # The writer completes the line: the span arrives, exactly once.
        journal.write_bytes(full)
        agg.poll_once()
        spans = agg.stitched()["spans"]
        assert len([s for s in spans if s["stage"] == "acquire"]) == 1
    finally:
        agg.close()


def test_journal_truncation_between_polls_resets_cursor(tmp_path):
    """Rotation/truncation regression: when the journal shrinks below
    the aggregator's cursor (logrotate, crash-dump rewrite), the reader
    must restart from offset 0 instead of seeking past EOF and reading
    nothing forever."""
    from fishnet_tpu.telemetry.spans import SpanRecorder
    from fishnet_tpu.telemetry.tracing import batch_root

    journal = tmp_path / "PROC0.journal.jsonl"
    rec = SpanRecorder()
    rec.journal_to(str(journal))
    for i in range(3):
        rec.record(
            "acquire", time.monotonic(), trace=batch_root(f"unit-{i}"),
            batch=f"unit-{i}",
        )
    rec.journal_close()

    agg = FleetAggregator(targets={}, journal_dir=str(tmp_path))
    try:
        agg.poll_once()
        spans = agg.stitched()["spans"]
        assert len([s for s in spans if s["stage"] == "acquire"]) == 3

        # The journal restarts smaller than the old cursor.
        journal.unlink()
        rec2 = SpanRecorder()
        rec2.journal_to(str(journal))
        rec2.record(
            "acquire", time.monotonic(), trace=batch_root("unit-x"),
            batch="unit-x",
        )
        rec2.journal_close()
        assert journal.stat().st_size < agg._journal_offsets[str(journal)]

        agg.poll_once()
        spans = agg.stitched()["spans"]
        batches = {
            s.get("batch") for s in spans if s["stage"] == "acquire"
        }
        assert "unit-x" in batches, batches
    finally:
        agg.close()


def test_poll_collects_profiles_and_console_renders_hot_stacks():
    """--profiles: each poll also scrapes /profile per up-target; the
    console appends the top-5 hottest-stacks panel, and a 503 (plane
    off) renders as "profiling off", never as a scrape error."""
    from fishnet_tpu.telemetry import profiler
    from fishnet_tpu.telemetry.fleet import render_console

    e0 = _proc_exporter(1)
    agg = FleetAggregator(targets={"PROC0": e0.url}, profiles=True)
    try:
        agg.poll_once()
        assert agg.fleet_doc()["procs"]["PROC0"]["up"] is True
        frame = render_console(agg, profiles=True)
        assert "HOT STACKS" in frame
        assert "profiling off" in frame

        prof = profiler.start(hz=200)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and prof.samples < 5:
            time.sleep(0.02)
        agg.poll_once()
        frame = render_console(agg, profiles=True)
        assert "samples @" in frame
        assert "profiling off" not in frame
        # Without the flag the panel never renders.
        assert "HOT STACKS" not in render_console(agg)
    finally:
        profiler.stop()
        agg.close()
        e0.close()
