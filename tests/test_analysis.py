"""Tests for fishnet_tpu.analysis: each rule fires on its fixture at the
right file:line, suppressions behave, the CLI round-trips exit codes —
and the TREE IS CLEAN (the tier-1 gate that makes the checker binding:
any reintroduced R1-R9 violation fails CI here, not in review).
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

from fishnet_tpu.analysis.contracts import EscapeHatchRule, TelemetryContractRule
from fishnet_tpu.analysis.donation import DonationSafetyRule
from fishnet_tpu.analysis.engine import (
    Project,
    check_paths,
    iter_python_files,
    to_json,
    to_sarif,
)
from fishnet_tpu.analysis.locks import LockOrderRule, build_lock_graph
from fishnet_tpu.analysis.registry import KNOBS, Knob
from fishnet_tpu.analysis.rules import (
    ALL_RULES,
    AsyncBlockingRule,
    CrossThreadStateRule,
    DeprecatedJaxRule,
    JitHostSyncRule,
    SwallowedExceptionRule,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"
PACKAGE = REPO / "fishnet_tpu"


def _lines(findings, rule=None):
    return sorted(
        (f.rule, f.line) for f in findings if rule is None or f.rule == rule
    )


# -- R1 -------------------------------------------------------------------


def test_r1_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r1_async_blocking.py"], [AsyncBlockingRule()]
    )
    assert _lines(findings) == [
        ("R1", 13),  # time.sleep
        ("R1", 17),  # aliased sleep
        ("R1", 21),  # subprocess.run
        ("R1", 25),  # requests.get
        ("R1", 29),  # un-awaited .communicate()
    ]


def test_r1_exempts_executor_and_nested_sync_defs():
    findings = check_paths(
        [FIXTURES / "r1_async_blocking.py"], [AsyncBlockingRule()]
    )
    flagged = {f.line for f in findings}
    # Nothing in fine() / sync_caller() (lines >= 33) may fire.
    assert all(line < 33 for line in flagged)


# -- R2 -------------------------------------------------------------------


def test_r2_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    assert _lines(findings) == [
        ("R2", 14),  # np.asarray in transitively-reached leaf
        ("R2", 19),  # branch on array truthiness (If)
        ("R2", 19),  # bool() concretization (same line)
        ("R2", 26),  # .item() in the decorated root
        ("R2", 31),  # float() in a jax.jit(partial(...))-assigned root
        ("R2", 69),  # np.asarray in a lambda-reached kernel nested def
        ("R2", 76),  # np.asarray in a pl.when-decorated `def _():`
        ("R2", 80),  # ... and in the SECOND `def _():` (qualname dedup)
    ]


def test_r2_reports_the_jit_root_for_transitive_hits():
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    by_line = {f.line: f for f in findings}
    assert "jitted_root" in by_line[14].message  # leaf blames its root


def test_r2_exempts_guards_statics_and_host_code():
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    flagged = {f.line for f in findings}
    # guarded() (is_concrete region), never_traced(), static_ok() clean
    # (lines 33-58; the fused-PSQT kernel fixture follows after).
    assert not any(33 <= line <= 58 for line in flagged)


def test_r2_reaches_fused_psqt_kernel_paths():
    """The fused-PSQT pallas_call entry point's kernel regions are in
    R2's call graph: host syncs inside a nested def reached only through
    a lambda argument, inside a `@pl.when`-decorated `def _():`, and
    inside a SECOND same-named `def _():` (engine qualname dedup) are
    all flagged and blamed on the kernel root."""
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    by_line = {f.line: f for f in findings}
    for line in (69, 76, 80):
        assert line in by_line, f"fused-PSQT violation at {line} not flagged"
        assert "_psqt_kernel" in by_line[line].message


# -- R3 -------------------------------------------------------------------


def test_r3_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r3_deprecated_jax.py"], [DeprecatedJaxRule()]
    )
    assert _lines(findings) == [
        ("R3", 5),  # import jax._src.xla_bridge
        ("R3", 6),  # from jax._src import core
        ("R3", 10),  # jax.core.Tracer
    ]
    tracer = [f for f in findings if f.line == 10][0]
    assert "is_concrete" in (tracer.suggestion or "")


# -- R4 -------------------------------------------------------------------


def test_r4_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert _lines(findings) == [
        ("R4", 11),  # module global from thread + async
        ("R4", 32),  # self._stopping unguarded in driver thread
        ("R4", 91),  # LeakyPipeline._seq unguarded in pack worker
        ("R4", 128),  # LeakyShardRouter._rungs unguarded ladder step
        ("R4", 162),  # LeakyStripedCache._entries unguarded insert
    ]


def test_r4_lock_guarded_class_is_clean():
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("CleanService" in f.message for f in findings)
    assert not any("_items" in f.message for f in findings)
    assert not any("_queue" in f.message for f in findings)


def test_r4_pack_decode_handoff_pattern():
    """The async-dispatch handoff (two worker threads + async
    submitters sharing lock-guarded state) is clean; the same shape
    with an unguarded worker-side bump is flagged."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("_inflight" in f.message for f in findings)
    assert not any("_ready" in f.message for f in findings)
    assert any("_seq" in f.message for f in findings)


def test_r4_shard_router_pattern():
    """The placement-aware serving shape (shard router + per-shard
    pipelines): lock-guarded ladder steps and drain re-routes shared
    between driver threads and async submitters are clean; the same
    shape with an unguarded thread-side rung bump is flagged."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("ShardRouterPattern" in f.message for f in findings)
    assert not any("_assign" in f.message for f in findings)
    assert any(
        "LeakyShardRouter" in f.message and "_rungs" in f.message
        for f in findings
    )


def test_r4_striped_cache_pattern():
    """The lock-striped eval-cache shape (search/eval_cache.EvalCache):
    driver-thread inserts and async probes sharing striped buckets are
    clean when every access holds the stripe lock; the same shape with
    an unguarded thread-side insert is flagged."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("StripedCachePattern" in f.message for f in findings)
    assert not any("_stripes" in f.message for f in findings)
    assert any(
        "LeakyStripedCache" in f.message and "_entries" in f.message
        for f in findings
    )


# -- R5 -------------------------------------------------------------------


def test_r5_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r5_swallowed.py"], [SwallowedExceptionRule()]
    )
    assert _lines(findings) == [
        ("R5", 12),  # bare except, pass-only
        ("R5", 19),  # except Exception, log-only (logging is invisible
        #              to the metrics plane — not observable)
        ("R5", 26),  # broad via tuple element
    ]


def test_r5_exempts_observable_handlers():
    # raise / counter .inc() / `return err` / set_exception(err) /
    # narrow types: all handled, none may fire (lines >= 30).
    findings = check_paths(
        [FIXTURES / "r5_swallowed.py"], [SwallowedExceptionRule()]
    )
    assert all(f.line < 30 for f in findings)


def test_r5_scopes_to_serving_layers():
    # The rule polices fishnet_tpu.net/sched/search (and stand-alone
    # files); an identical handler in, say, fishnet_tpu.train is out of
    # scope — broad excepts there have their own idioms (checkpoint
    # recovery) and their own review.
    rule = SwallowedExceptionRule()
    assert rule._SCOPES == (
        "fishnet_tpu.net", "fishnet_tpu.sched", "fishnet_tpu.search"
    )
    findings = check_paths([PACKAGE / "train"], [rule])
    assert findings == []


# -- R6 -------------------------------------------------------------------


def _package_project() -> Project:
    proj = Project()
    for path in iter_python_files([PACKAGE]):
        proj.add_file(path)
    return proj


def test_r6_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r6_lock_order.py"], [LockOrderRule()]
    )
    assert _lines(findings) == [
        ("R6", 36),  # pack->decode half of the cycle (call site)
        ("R6", 55),  # scrape lock reached under _pack_lock
        ("R6", 60),  # non-reentrant re-acquire via _sum()
    ]
    by_line = {f.line: f for f in findings}
    assert "cycle" in by_line[36].message
    assert "scrape" in by_line[55].message
    assert "not reentrant" in by_line[60].message


def test_r6_real_tree_lock_graph_crosses_threads_and_modules():
    """The cross-module contract behind R6: the static call graph must
    actually follow the platform's thread handoffs, or a clean run
    proves nothing. Driver threads are seeded from Thread(target=...),
    and the pack worker's dispatch must cross the CoalesceBackend seam
    into az_plane.py (virtual dispatch, not just name matching)."""
    graph = build_lock_graph(_package_project())
    entries = {fn.qualname for fn in graph.entry_points}
    # The serving plane's resident threads, found statically:
    for expected in (
        "SearchService._drive",
        "_AsyncDispatchPipeline._pack_loop",
        "_AsyncDispatchPipeline._decode_loop",
        "AzMctsService._drive",
        "FleetAggregator._run",
    ):
        assert expected in entries, f"{expected} not seeded as an entry"
    by_qualname = {}
    for fn in graph.callees:
        by_qualname.setdefault(fn.qualname, fn)
    # SearchService._drive hands work to the coalescer...
    drive = by_qualname["SearchService._drive"]
    reached = {fn.qualname for fn in graph.reachable_from(drive)}
    assert "_DispatchCoalescer.submit" in reached
    # ...and the pack worker's flush crosses the CoalesceBackend seam
    # into the AZ plane's module (az_plane.py), not just service.py.
    pack = by_qualname["_AsyncDispatchPipeline._pack_loop"]
    pack_mods = {
        fn.module.name for fn in graph.reachable_from(pack)
    }
    assert "fishnet_tpu.search.az_plane" in pack_mods
    # The AZ plane's evaluate() rides the SAME coalescer object.
    az_eval = by_qualname["AzDispatchPlane.evaluate"]
    az_reached = {fn.qualname for fn in graph.reachable_from(az_eval)}
    assert "_DispatchCoalescer.submit" in az_reached


def test_r6_real_tree_canonical_order_holds():
    """The canonical lock-order table (doc/static-analysis.md) is not
    aspirational: the real graph has the documented edges, no cycles,
    and the scrape lock is identified."""
    graph = build_lock_graph(_package_project())
    assert graph.scrape_lock is not None
    assert graph.scrape_lock.endswith("_scrape_lock")
    edge_pairs = set(graph.edges)
    # The mesh serving chain: mesh_lock above the coalescer above the
    # router (doc/static-analysis.md "Canonical lock order").
    assert any(
        "mesh_lock" in outer and "_DispatchCoalescer._lock" in inner
        for outer, inner in edge_pairs
    )
    assert any(
        "_DispatchCoalescer._lock" in outer and "ShardRouter._lock" in inner
        for outer, inner in edge_pairs
    )
    # No edge may point BACK UP from the router (leaf lock).
    assert not any(
        "ShardRouter._lock" in outer for outer, _inner in edge_pairs
    )


# -- R7 -------------------------------------------------------------------


def test_r7_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r7_telemetry_contract.py"],
        [TelemetryContractRule(doc_path=FIXTURES / "r7_observability.md")],
    )
    assert _lines(findings) == [
        ("R7", 11),  # doc row fishnet_fixture_orphan_total: no emitter
        ("R7", 14),  # fishnet_fixture_depth emitted, not documented
        ("R7", 15),  # doc stage fixture_decode never recorded
        ("R7", 16),  # fishnet_fixture_errors_total label drift (tenant)
        ("R7", 22),  # span stage fixture_pack not documented
    ]
    doc_findings = [
        f for f in findings if f.path.endswith("r7_observability.md")
    ]
    assert {f.line for f in doc_findings} == {11, 15}


def test_r7_real_tree_contract_holds():
    """Every fishnet_* family and span stage emitted by the package has
    a doc row (and vice versa) — the drift this PR fixed stays fixed."""
    findings = check_paths([PACKAGE], [TelemetryContractRule()])
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- R8 -------------------------------------------------------------------

_FIXTURE_KNOBS = (
    Knob("FISHNET_FIXTURE_DECLARED", "env", "unset", "doc/install.md"),
    Knob("--fixture-declared", "cli", "unset", "doc/install.md"),
)


def test_r8_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r8_escape_hatch.py"],
        [EscapeHatchRule(knobs=_FIXTURE_KNOBS)],
    )
    assert _lines(findings) == [
        ("R8", 11),  # os.environ.get("FISHNET_FIXTURE_UNDECLARED")
        ("R8", 14),  # ROGUE_ENV = "FISHNET_FIXTURE_ROGUE" name constant
        ("R8", 24),  # add_argument("--fixture-undeclared")
    ]


def test_r8_registry_pointers_are_live():
    """Registry hygiene beyond the rule run: every declared knob's
    documented_in/tested_by names a real file that mentions the knob."""
    for knob in KNOBS:
        probe = knob.name.lstrip("-")
        for pointer in (knob.documented_in, knob.tested_by):
            if pointer is None:
                continue
            target = REPO / pointer
            assert target.exists(), f"{knob.name}: {pointer} missing"
            assert probe in target.read_text(encoding="utf-8"), (
                f"{knob.name}: {pointer} never mentions it"
            )


def test_r8_real_tree_contract_holds():
    findings = check_paths([PACKAGE], [EscapeHatchRule()])
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- R9 -------------------------------------------------------------------


def test_r9_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r9_donation.py"], [DonationSafetyRule()]
    )
    assert _lines(findings) == [
        ("R9", 23),  # module-level wrapper: `state` read after donation
        ("R9", 33),  # partial(jax.jit) decorator: `buf` read after
        ("R9", 46),  # self._fj attr wrapper: `self._buf` read after
    ]


def test_r9_ping_pong_rebinds_are_clean():
    findings = check_paths(
        [FIXTURES / "r9_donation.py"], [DonationSafetyRule()]
    )
    flagged = {f.line for f in findings}
    # train_good / run_good (the rebind idiom) never fire.
    assert not any(26 <= line <= 28 for line in flagged)
    assert not any(48 <= line <= 50 for line in flagged)


# -- suppressions ---------------------------------------------------------


def test_suppressions():
    findings = check_paths([FIXTURES / "suppressions.py"], [AsyncBlockingRule()])
    assert _lines(findings) == [
        ("R1", 17),  # wrong-rule suppression does not apply
        ("SUP", 13),  # suppression without justification is itself flagged
    ]


def test_stale_suppression_detection(tmp_path):
    """A suppression that stops matching becomes an error — but only
    when the rules it names actually ran, and never for backtick-quoted
    doc examples of the syntax."""
    f = tmp_path / "stale.py"
    f.write_text(
        '"""Doc example: `# fishnet: ignore[R1] -- quoted, not live`."""\n'
        "import time\n"
        "\n"
        "\n"
        "def sync_ok():\n"
        "    time.sleep(1)  # fishnet: ignore[R1] -- not async, never fired\n"
    )
    stale = check_paths([f], [AsyncBlockingRule()])
    assert _lines(stale) == [("SUP", 6)]  # line 1's quoted example exempt
    # Under a run that does NOT include R1 the comment is not judged.
    assert check_paths([f], [DeprecatedJaxRule()]) == []


# -- the repo gate --------------------------------------------------------


def test_fishnet_tpu_tree_is_clean():
    """THE tier-1 invariant: the package tree passes its own checker.

    If this fails, either fix the flagged code or add a justified
    inline suppression (`# fishnet: ignore[Rn] -- why`) — see
    doc/static-analysis.md.
    """
    findings = check_paths([PACKAGE], ALL_RULES)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- CLI ------------------------------------------------------------------


def test_cli_exit_codes():
    clean = subprocess.run(
        [sys.executable, "-m", "fishnet_tpu.analysis", str(PACKAGE), "-q"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    dirty = subprocess.run(
        [
            sys.executable,
            "-m",
            "fishnet_tpu.analysis",
            str(FIXTURES / "r1_async_blocking.py"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert dirty.returncode == 1
    assert "R1" in dirty.stdout
    rules = subprocess.run(
        [sys.executable, "-m", "fishnet_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert rules.returncode == 0
    for rid in ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9"):
        assert rid in rules.stdout


def test_cli_unknown_rule_exits_2_with_known_list():
    """`--rules` with an unknown id must fail usage (2), and the error
    must LIST the known rules — a bare "unknown rule" message sends the
    user off to read the source."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "fishnet_tpu.analysis",
            "--rules",
            "R1,R99",
            str(FIXTURES / "r1_async_blocking.py"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 2
    assert "R99" in proc.stderr
    for rid in ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9"):
        assert rid in proc.stderr, f"{rid} missing from the known-rule list"


def test_cli_json_and_sarif_outputs(tmp_path):
    json_out = tmp_path / "findings.json"
    sarif_out = tmp_path / "findings.sarif"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "fishnet_tpu.analysis",
            str(FIXTURES / "r1_async_blocking.py"),
            "--json",
            str(json_out),
            "--sarif",
            str(sarif_out),
            "-q",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 1  # findings still drive the exit code
    payload = json.loads(json_out.read_text())
    assert [f["rule"] for f in payload] == ["R1"] * 5
    assert {"rule", "path", "line", "col", "message", "suggestion"} <= set(
        payload[0]
    )
    sarif = json.loads(sarif_out.read_text())
    assert sarif["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in sarif["$schema"]
    run = sarif["runs"][0]
    assert len(run["results"]) == 5
    ids = {d["id"] for d in run["tool"]["driver"]["rules"]}
    assert {"R1", "R9"} <= ids
    loc = run["results"][0]["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 13


def test_findings_sorted_deterministically():
    """check_paths output is sorted by (path, line, col, rule) so CI
    diffs are stable run to run, and to_json preserves that order."""
    findings = check_paths(
        [FIXTURES / "r6_lock_order.py", FIXTURES / "r1_async_blocking.py"],
        [LockOrderRule(), AsyncBlockingRule()],
    )
    keys = [(f.path, f.line, f.col, f.rule) for f in findings]
    assert keys == sorted(keys)
    assert {f.rule for f in findings} == {"R1", "R6"}
    assert [d["line"] for d in to_json(findings)] == [f.line for f in findings]


def test_sarif_rule_descriptors_cover_sup_and_ast():
    from fishnet_tpu.analysis.engine import Finding

    findings = [
        Finding(rule="SUP", path="x.py", line=1, col=0, message="stale"),
        Finding(rule="AST", path="y.py", line=1, col=0, message="bad parse"),
    ]
    sarif = to_sarif(findings, ALL_RULES)
    ids = {d["id"] for d in sarif["runs"][0]["tool"]["driver"]["rules"]}
    assert {"SUP", "AST"} <= ids


def test_r4_plain_call_context_manager_is_skipped():
    """`with open(...)` (a Name-func call) inside a thread-bearing
    class must not crash _lock_spans, and the guarded JournalReader
    stays clean."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("JournalReader" in f.message for f in findings)
    assert not any("_offsets" in f.message for f in findings)
