"""Tests for fishnet_tpu.analysis: each rule fires on its fixture at the
right file:line, suppressions behave, the CLI round-trips exit codes —
and the TREE IS CLEAN (the tier-1 gate that makes the checker binding:
any reintroduced R1-R4 violation fails CI here, not in review).
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

from fishnet_tpu.analysis.engine import check_paths
from fishnet_tpu.analysis.rules import (
    ALL_RULES,
    AsyncBlockingRule,
    CrossThreadStateRule,
    DeprecatedJaxRule,
    JitHostSyncRule,
    SwallowedExceptionRule,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"
PACKAGE = REPO / "fishnet_tpu"


def _lines(findings, rule=None):
    return sorted(
        (f.rule, f.line) for f in findings if rule is None or f.rule == rule
    )


# -- R1 -------------------------------------------------------------------


def test_r1_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r1_async_blocking.py"], [AsyncBlockingRule()]
    )
    assert _lines(findings) == [
        ("R1", 13),  # time.sleep
        ("R1", 17),  # aliased sleep
        ("R1", 21),  # subprocess.run
        ("R1", 25),  # requests.get
        ("R1", 29),  # un-awaited .communicate()
    ]


def test_r1_exempts_executor_and_nested_sync_defs():
    findings = check_paths(
        [FIXTURES / "r1_async_blocking.py"], [AsyncBlockingRule()]
    )
    flagged = {f.line for f in findings}
    # Nothing in fine() / sync_caller() (lines >= 33) may fire.
    assert all(line < 33 for line in flagged)


# -- R2 -------------------------------------------------------------------


def test_r2_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    assert _lines(findings) == [
        ("R2", 14),  # np.asarray in transitively-reached leaf
        ("R2", 19),  # branch on array truthiness (If)
        ("R2", 19),  # bool() concretization (same line)
        ("R2", 26),  # .item() in the decorated root
        ("R2", 31),  # float() in a jax.jit(partial(...))-assigned root
        ("R2", 69),  # np.asarray in a lambda-reached kernel nested def
        ("R2", 76),  # np.asarray in a pl.when-decorated `def _():`
        ("R2", 80),  # ... and in the SECOND `def _():` (qualname dedup)
    ]


def test_r2_reports_the_jit_root_for_transitive_hits():
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    by_line = {f.line: f for f in findings}
    assert "jitted_root" in by_line[14].message  # leaf blames its root


def test_r2_exempts_guards_statics_and_host_code():
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    flagged = {f.line for f in findings}
    # guarded() (is_concrete region), never_traced(), static_ok() clean
    # (lines 33-58; the fused-PSQT kernel fixture follows after).
    assert not any(33 <= line <= 58 for line in flagged)


def test_r2_reaches_fused_psqt_kernel_paths():
    """The fused-PSQT pallas_call entry point's kernel regions are in
    R2's call graph: host syncs inside a nested def reached only through
    a lambda argument, inside a `@pl.when`-decorated `def _():`, and
    inside a SECOND same-named `def _():` (engine qualname dedup) are
    all flagged and blamed on the kernel root."""
    findings = check_paths(
        [FIXTURES / "r2_jit_host_sync.py"], [JitHostSyncRule()]
    )
    by_line = {f.line: f for f in findings}
    for line in (69, 76, 80):
        assert line in by_line, f"fused-PSQT violation at {line} not flagged"
        assert "_psqt_kernel" in by_line[line].message


# -- R3 -------------------------------------------------------------------


def test_r3_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r3_deprecated_jax.py"], [DeprecatedJaxRule()]
    )
    assert _lines(findings) == [
        ("R3", 5),  # import jax._src.xla_bridge
        ("R3", 6),  # from jax._src import core
        ("R3", 10),  # jax.core.Tracer
    ]
    tracer = [f for f in findings if f.line == 10][0]
    assert "is_concrete" in (tracer.suggestion or "")


# -- R4 -------------------------------------------------------------------


def test_r4_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert _lines(findings) == [
        ("R4", 11),  # module global from thread + async
        ("R4", 32),  # self._stopping unguarded in driver thread
        ("R4", 91),  # LeakyPipeline._seq unguarded in pack worker
        ("R4", 128),  # LeakyShardRouter._rungs unguarded ladder step
        ("R4", 162),  # LeakyStripedCache._entries unguarded insert
    ]


def test_r4_lock_guarded_class_is_clean():
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("CleanService" in f.message for f in findings)
    assert not any("_items" in f.message for f in findings)
    assert not any("_queue" in f.message for f in findings)


def test_r4_pack_decode_handoff_pattern():
    """The async-dispatch handoff (two worker threads + async
    submitters sharing lock-guarded state) is clean; the same shape
    with an unguarded worker-side bump is flagged."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("_inflight" in f.message for f in findings)
    assert not any("_ready" in f.message for f in findings)
    assert any("_seq" in f.message for f in findings)


def test_r4_shard_router_pattern():
    """The placement-aware serving shape (shard router + per-shard
    pipelines): lock-guarded ladder steps and drain re-routes shared
    between driver threads and async submitters are clean; the same
    shape with an unguarded thread-side rung bump is flagged."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("ShardRouterPattern" in f.message for f in findings)
    assert not any("_assign" in f.message for f in findings)
    assert any(
        "LeakyShardRouter" in f.message and "_rungs" in f.message
        for f in findings
    )


def test_r4_striped_cache_pattern():
    """The lock-striped eval-cache shape (search/eval_cache.EvalCache):
    driver-thread inserts and async probes sharing striped buckets are
    clean when every access holds the stripe lock; the same shape with
    an unguarded thread-side insert is flagged."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("StripedCachePattern" in f.message for f in findings)
    assert not any("_stripes" in f.message for f in findings)
    assert any(
        "LeakyStripedCache" in f.message and "_entries" in f.message
        for f in findings
    )


# -- R5 -------------------------------------------------------------------


def test_r5_fires_on_known_lines():
    findings = check_paths(
        [FIXTURES / "r5_swallowed.py"], [SwallowedExceptionRule()]
    )
    assert _lines(findings) == [
        ("R5", 12),  # bare except, pass-only
        ("R5", 19),  # except Exception, log-only (logging is invisible
        #              to the metrics plane — not observable)
        ("R5", 26),  # broad via tuple element
    ]


def test_r5_exempts_observable_handlers():
    # raise / counter .inc() / `return err` / set_exception(err) /
    # narrow types: all handled, none may fire (lines >= 30).
    findings = check_paths(
        [FIXTURES / "r5_swallowed.py"], [SwallowedExceptionRule()]
    )
    assert all(f.line < 30 for f in findings)


def test_r5_scopes_to_serving_layers():
    # The rule polices fishnet_tpu.net/sched/search (and stand-alone
    # files); an identical handler in, say, fishnet_tpu.train is out of
    # scope — broad excepts there have their own idioms (checkpoint
    # recovery) and their own review.
    rule = SwallowedExceptionRule()
    assert rule._SCOPES == (
        "fishnet_tpu.net", "fishnet_tpu.sched", "fishnet_tpu.search"
    )
    findings = check_paths([PACKAGE / "train"], [rule])
    assert findings == []


# -- suppressions ---------------------------------------------------------


def test_suppressions():
    findings = check_paths([FIXTURES / "suppressions.py"], [AsyncBlockingRule()])
    assert _lines(findings) == [
        ("R1", 17),  # wrong-rule suppression does not apply
        ("SUP", 13),  # suppression without justification is itself flagged
    ]


# -- the repo gate --------------------------------------------------------


def test_fishnet_tpu_tree_is_clean():
    """THE tier-1 invariant: the package tree passes its own checker.

    If this fails, either fix the flagged code or add a justified
    inline suppression (`# fishnet: ignore[Rn] -- why`) — see
    doc/static-analysis.md.
    """
    findings = check_paths([PACKAGE], ALL_RULES)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- CLI ------------------------------------------------------------------


def test_cli_exit_codes():
    clean = subprocess.run(
        [sys.executable, "-m", "fishnet_tpu.analysis", str(PACKAGE), "-q"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    dirty = subprocess.run(
        [
            sys.executable,
            "-m",
            "fishnet_tpu.analysis",
            str(FIXTURES / "r1_async_blocking.py"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert dirty.returncode == 1
    assert "R1" in dirty.stdout
    rules = subprocess.run(
        [sys.executable, "-m", "fishnet_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert rules.returncode == 0
    for rid in ("R1", "R2", "R3", "R4", "R5"):
        assert rid in rules.stdout


def test_r4_plain_call_context_manager_is_skipped():
    """`with open(...)` (a Name-func call) inside a thread-bearing
    class must not crash _lock_spans, and the guarded JournalReader
    stays clean."""
    findings = check_paths(
        [FIXTURES / "r4_cross_thread.py"], [CrossThreadStateRule()]
    )
    assert not any("JournalReader" in f.message for f in findings)
    assert not any("_offsets" in f.message for f in findings)
