"""Perf-regression sentinel (telemetry/regress.py, ISSUE 15): the
checked-in bench artifacts must judge clean (exit 0, >=10 tracked
series — the acceptance floor), a doctored artifact must gate (exit 1),
a missing/empty root exits 2, and the direction/zero/true judging rules
plus the legacy-wrapper tail recovery are pinned as units."""

import json
import os
import shutil

import pytest

from fishnet_tpu.telemetry import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARTIFACT_PREFIXES = ("BENCH_", "MULTICHIP_", "CLUSTER_", "MCTS_")


def _copy_artifacts(dst: str) -> int:
    n = 0
    for fname in sorted(os.listdir(REPO)):
        if fname.endswith(".json") and fname.startswith(ARTIFACT_PREFIXES):
            shutil.copy(os.path.join(REPO, fname), os.path.join(dst, fname))
            n += 1
    return n


# -- the acceptance run over the checked-in artifacts -------------------------


def test_checked_in_artifacts_judge_clean(capsys):
    """The repo's own artifact history must not gate: the sentinel over
    the 15 checked-in BENCH/MULTICHIP/CLUSTER/MCTS runs exits 0 and
    tracks at least 10 series (the ISSUE acceptance floor)."""
    rc = regress.main(["--root", REPO, "--no-write"])
    assert rc == 0
    report = regress.build_report(REPO)
    assert report["artifacts_ingested"] >= 15
    assert report["series_tracked"] >= 10
    assert report["status"] == "ok"
    assert report["gated_regressions"] == []
    # The table printer names every gated metric family prefix.
    out = capsys.readouterr().out
    assert "series" in out


def test_checked_in_report_matches_repo_copy():
    """REGRESS_r01.json in the repo is a real run of this tool over
    these artifacts — same format tag and a clean status."""
    with open(os.path.join(REPO, "REGRESS_r01.json")) as fp:
        checked_in = json.load(fp)
    assert checked_in["format"] == "fishnet-regress/1"
    assert checked_in["status"] == "ok"
    assert checked_in["series_tracked"] >= 10


def test_doctored_artifact_gates(tmp_path):
    """Halving the latest MCTS warm visits/s (a gate-severity
    up-direction series with a 20% band) must flip the report to
    regression and the CLI to exit 1."""
    root = str(tmp_path)
    assert _copy_artifacts(root) >= 15
    latest = os.path.join(root, "MCTS_r02.json")
    with open(os.path.join(root, "MCTS_r01.json")) as fp:
        doc = json.load(fp)
    doc["value"] = doc["value"] * 0.5
    with open(latest, "w") as fp:
        json.dump(doc, fp)

    report = regress.build_report(root)
    assert report["status"] == "regression"
    assert any("mcts" in m.lower() for m in report["gated_regressions"])
    rc = regress.main(["--root", root, "--no-write"])
    assert rc == 1


def test_watch_severity_does_not_gate(tmp_path):
    """A watch-severity regression is reported but never gates: halve
    a MULTICHIP watch metric (steps_per_s) while keeping its gate
    parity bits intact — status stays ok, exit stays 0."""
    root = str(tmp_path)
    _copy_artifacts(root)
    with open(os.path.join(root, "MULTICHIP_r06.json")) as fp:
        doc = json.load(fp)
    doc["value"] = doc["value"] * 0.5
    with open(os.path.join(root, "MULTICHIP_r07.json"), "w") as fp:
        json.dump(doc, fp)
    report = regress.build_report(root)
    assert report["status"] == "ok"
    assert any(
        "steps_per_s" in m for m in report["regressions"]
    ), report["regressions"]


def test_report_written_with_next_run_number(tmp_path):
    root = str(tmp_path)
    _copy_artifacts(root)
    rc = regress.main(["--root", root])
    assert rc == 0
    assert os.path.exists(os.path.join(root, "REGRESS_r01.json"))
    # Next invocation numbers past the existing report.
    assert regress._next_out_path(root).endswith("REGRESS_r02.json")


def test_missing_and_empty_roots_exit_2(tmp_path):
    assert regress.main(["--root", str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert regress.main(["--root", str(empty), "--no-write"]) == 2


# -- judging rules ------------------------------------------------------------


def _series(spec, points):
    s = regress._Series(spec=spec)
    for run, val in points.items():
        s.points[run] = (val, f"{spec.prefix}_{run}.json")
    return s


def test_judge_directions():
    up = regress.Spec("X", "m", "value", "up", 0.10, "gate")
    down = regress.Spec("X", "m", "value", "down", 0.10, "gate")
    zero = regress.Spec("X", "m", "value", "zero", 0.0, "gate")
    true = regress.Spec("X", "m", "value", "true", 0.0, "gate")

    assert regress._judge(_series(up, {"r01": 100, "r02": 95}))[
        "verdict"] == "ok"  # -5% within 10% band
    assert regress._judge(_series(up, {"r01": 100, "r02": 80}))[
        "verdict"] == "regression"
    assert regress._judge(_series(down, {"r01": 100, "r02": 120}))[
        "verdict"] == "regression"
    assert regress._judge(_series(down, {"r01": 100, "r02": 105}))[
        "verdict"] == "ok"
    assert regress._judge(_series(zero, {"r01": 0.0}))["verdict"] == "ok"
    assert regress._judge(_series(zero, {"r01": 2.0}))[
        "verdict"] == "regression"
    assert regress._judge(_series(true, {"r01": 1.0}))["verdict"] == "ok"
    assert regress._judge(_series(true, {"r01": 0.0}))[
        "verdict"] == "regression"
    assert regress._judge(_series(up, {"r01": 100}))[
        "verdict"] == "single-point"


def test_judge_compares_latest_to_nearest_prior():
    """Only the newest step is judged: an old regression between r01
    and r02 must not flag once r03 recovers."""
    up = regress.Spec("X", "m", "value", "up", 0.10, "gate")
    row = regress._judge(_series(up, {"r01": 100, "r02": 50, "r03": 51}))
    assert row["verdict"] == "ok"
    assert row["prior_run"] == "r02"


def test_resolve_dotted_paths_lists_and_bools():
    doc = {"a": {"b": 3.5}, "lost": [1, 2], "ok": True}
    assert regress._resolve(doc, "a.b") == 3.5
    assert regress._resolve(doc, "lost") == 2.0  # lists -> len
    assert regress._resolve(doc, "ok") == 1.0
    assert regress._resolve(doc, "a.missing") is None


def test_legacy_wrapper_tail_recovery():
    """BENCH_r01..r05 are legacy wrappers (parsed=null, front-truncated
    JSON in "tail"): ingest must still recover the regexable headline
    series from them."""
    store, log = regress.ingest(REPO)
    legacy = [a for a in log if a["file"] == "BENCH_r02.json"]
    assert legacy and legacy[0]["legacy"]
    recovered = [
        key for key, s in store.items()
        if "r02" in s.points and key.startswith("BENCH/legacy_")
    ]
    assert recovered, "no series recovered from the legacy tail"
    # Legacy recovery is watch-severity only: a noisy regexed tail must
    # never gate CI.
    assert all(
        store[k].spec.severity == "watch" for k in store
        if k.startswith("BENCH/legacy_")
    )
