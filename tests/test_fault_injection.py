"""Failure-detection / recovery paths (SURVEY.md §5): a dead search
service is detected and replaced, in-flight work fails cleanly, and the
client keeps serving after the restart."""

import asyncio
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
from fake_server import FakeServer  # noqa: E402
from test_client_e2e import make_client, wait_for  # noqa: E402

from fishnet_tpu.chess.core import NativeCoreError
from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.protocol.types import EngineFlavor
from fishnet_tpu.search.service import SearchService

pytestmark = pytest.mark.anyio


def make_service():
    return SearchService(
        weights=NnueWeights.random(seed=0), pool_slots=16,
        batch_capacity=64, tt_bytes=8 << 20, backend="scalar",
    )


async def test_close_unwinds_inflight_searches_promptly():
    # A 50M-node scalar search would run for minutes; close() must unwind
    # it promptly (stop-all), resolving the caller with either a partial
    # result (search stopped in time) or a shutdown error — never a hang.
    service = make_service()
    task = asyncio.create_task(
        service.search("rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
                       [], nodes=50_000_000)
    )
    await asyncio.sleep(0.3)
    service.close()
    try:
        result = await asyncio.wait_for(task, 30)
        assert result.nodes < 50_000_000  # stopped early, partial result
    except NativeCoreError:
        pass  # shutdown beat the harvest: equally acceptable
    assert not service.is_alive()


async def test_factory_replaces_dead_service():
    service = make_service()
    rebuilt = []

    def builder():
        svc = make_service()
        rebuilt.append(svc)
        return svc

    factory = TpuNnueEngineFactory(service, service_builder=builder)
    service.close()
    engine = await factory.create(EngineFlavor.OFFICIAL)
    assert rebuilt and factory.service is rebuilt[0]
    assert factory.service.is_alive()
    res = await engine.service.search(
        "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], depth=3
    )
    assert res.best_move == "d1d8"
    for svc in rebuilt:
        svc.close()


async def test_client_recovers_from_service_death():
    service = make_service()
    services = [service]

    def builder():
        svc = make_service()
        services.append(svc)
        return svc

    async with FakeServer() as server:
        first = server.lichess.add_analysis_job(moves="e2e4", nodes=2000)
        client = make_client(
            server.endpoint, cores=1,
            engine_factory=TpuNnueEngineFactory(service, service_builder=builder),
        )
        await client.start()
        assert await wait_for(lambda: first in server.lichess.analyses)

        # Kill the shared service under the running client. The next
        # job's position fails, is REQUEUED (bounded generations,
        # sched/queue.py), the worker restarts its engine via the
        # factory, and the REPLACEMENT service completes the batch —
        # transient service death no longer loses acquired work.
        service.close()
        sacrificial = server.lichess.add_analysis_job(moves="d2d4", nodes=2000)
        for _ in range(100):
            if rebuilt := services[1:]:
                break
            await asyncio.sleep(0.2)
        recovered = server.lichess.add_analysis_job(moves="g1f3", nodes=2000)
        assert await wait_for(
            lambda: recovered in server.lichess.analyses, timeout=60
        )
        assert await wait_for(
            lambda: sacrificial in server.lichess.analyses, timeout=60
        )
        assert (
            server.lichess.analysis_submission_counts[sacrificial] == 1
        )  # recovered exactly once, not duplicated
        await client.stop()
    for svc in services:
        svc.close()


async def test_concurrent_creates_rebuild_exactly_once():
    # After a service death, N workers restart at once; the factory must
    # serialize the rebuild so N-1 services are not built and leaked.
    service = make_service()
    rebuilt = []

    def builder():
        svc = make_service()
        rebuilt.append(svc)
        return svc

    factory = TpuNnueEngineFactory(service, service_builder=builder)
    service.close()
    engines = await asyncio.gather(
        *(factory.create(EngineFlavor.OFFICIAL) for _ in range(6))
    )
    assert len(rebuilt) == 1
    assert all(e.service is rebuilt[0] for e in engines)
    rebuilt[0].close()
