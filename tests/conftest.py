"""Test configuration.

Tests run on CPU with a virtual 8-device platform so that every sharding
path (mesh construction, pjit/shard_map collectives) is exercised without
TPU hardware. This must be set before jax is first imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


@pytest.fixture
def anyio_backend():
    # aiohttp requires asyncio; never run async tests on trio.
    return "asyncio"
