"""Test configuration.

Tests run on CPU with a virtual 8-device platform so that every sharding
path (mesh construction, pjit/shard_map collectives) is exercised without
TPU hardware. This must be set before jax is first imported anywhere.
"""

import os

# Tests must never claim the real TPU. The axon plugin registers its
# backend factory at interpreter start (sitecustomize) and its hooks can
# initialize the TPU tunnel even under JAX_PLATFORMS=cpu, so drop the
# factory outright before any backend is initialized.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: full-spec-shape tests (heavier)")


@pytest.fixture
def anyio_backend():
    # aiohttp requires asyncio; never run async tests on trio.
    return "asyncio"


@pytest.fixture(autouse=True)
def _fresh_eval_cache(monkeypatch):
    # The position-keyed eval cache is process-wide BY DESIGN (it
    # outlives services to survive respawns), which in a shared pytest
    # process would couple tests: a warm cache turns later tests'
    # dispatches into whole-batch skips and skews every dispatch-count
    # assertion. Reset around each test; warm-cache behavior is
    # exercised explicitly inside tests/test_eval_cache.py.
    #
    # Bounds seeding and speculative pad-row evals are likewise pinned
    # off by default: both legitimately change node counts and
    # prewire-hit totals, which dozens of older tests assert exactly.
    # Tests that exercise them monkeypatch the hatches back off.
    from fishnet_tpu.search import eval_cache

    monkeypatch.setenv("FISHNET_NO_BOUNDS", "1")
    monkeypatch.setenv("FISHNET_NO_SPECULATION", "1")
    eval_cache.reset_cache()
    yield
    eval_cache.reset_cache()
