"""R7 fixture: telemetry-contract drift against r7_observability.md.
Line numbers are asserted by tests/test_analysis.py — edit with care."""

REGISTRY = None
_SPANS = None


def serve(n):
    # Documented family with matching labels: fine.
    REGISTRY.counter(
        "fishnet_fixture_requests_total", "requests", labelnames=("code",)
    ).inc()
    # VIOLATION line 14: emitted but not mentioned in the doc.
    REGISTRY.gauge("fishnet_fixture_depth", "queue depth").set(n)
    # VIOLATION line 16: documented labels are {code}; code says {code, tenant}.
    REGISTRY.counter(
        "fishnet_fixture_errors_total",
        "errors",
        labelnames=("code", "tenant"),
    ).inc()
    # VIOLATION line 22: span stage never documented in a Stage table.
    with _SPANS.record("fixture_pack"):
        pass
