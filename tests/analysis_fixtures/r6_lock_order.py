"""R6 fixture: lock-order violations. Line numbers are asserted by
tests/test_analysis.py — edit with care."""

import threading

registry = None


def register_collector(fn):
    registry.append(fn)


class Registry:
    """A metrics registry shape: collect() holds the scrape lock across
    every registered collector callback."""

    def __init__(self):
        self._scrape_lock = threading.Lock()
        self._collectors = []

    def collect(self):
        with self._scrape_lock:
            for fn in self._collectors:
                fn()


class Pipeline:
    def __init__(self):
        self._pack_lock = threading.Lock()
        self._decode_lock = threading.Lock()
        self._registry = Registry()

    def pack(self):
        # pack -> decode ... (cycle reported at line 36, the call site)
        with self._pack_lock:
            self._finish_decode()

    def _finish_decode(self):
        with self._decode_lock:
            pass

    def decode(self):
        # ... while decode -> pack: VIOLATION (cycle)
        with self._decode_lock:
            self._repack()

    def _repack(self):
        with self._pack_lock:
            pass

    def close(self):
        # VIOLATION: reaches the scrape lock while holding _pack_lock
        # (the exporter-close inversion family), line 55
        with self._pack_lock:
            self._registry.collect()

    def stats(self):
        # VIOLATION: re-acquire of a non-reentrant lock, line 60
        with self._pack_lock:
            self._sum()

    def _sum(self):
        with self._pack_lock:
            return 0
