"""R3 fixture: deprecated/private JAX API. Line numbers are asserted by
tests/test_analysis.py — edit with care."""

import jax
import jax._src.xla_bridge as xb  # VIOLATION line 5
from jax._src import core as private_core  # VIOLATION line 6


def uses_tracer(x):
    return isinstance(x, jax.core.Tracer)  # VIOLATION line 10


def fine(x):
    import jax.numpy as jnp

    return jnp.asarray(x)
