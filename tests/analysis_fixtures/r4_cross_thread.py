"""R4 fixture: unsynchronized cross-thread instance/module state. Line
numbers are asserted by tests/test_analysis.py — edit with care."""

import threading

_counter = 0


def _thread_main():
    global _counter
    _counter += 1  # VIOLATION (global: thread side), line 11


async def bump():
    global _counter
    _counter += 1  # (global: async side; thread-side line is reported)


def start():
    threading.Thread(target=_thread_main).start()


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._stopping = False
        self._thread = threading.Thread(target=self._drive)

    def _drive(self):
        while True:
            self._stopping = True  # VIOLATION line 32 (no lock, also async)
            with self._lock:
                self._items.pop()  # guarded: fine

    async def submit(self, item):
        with self._lock:
            self._items.append(item)  # guarded: fine
        self._stopping = False  # async-side mutation of the same flag


class CleanService:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._queue.clear()

    async def push(self, x):
        with self._lock:
            self._queue.append(x)


class PackDecodePipeline:
    """The async-dispatch handoff pattern (search/service.py
    _AsyncDispatchPipeline): a pack and a decode worker thread feed
    each other through queues while submitters park work from async
    context; every shared-state site is lock-guarded. Must be clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ready = []
        self._inflight = 0
        self._pack = threading.Thread(target=self._pack_loop)
        self._decode = threading.Thread(target=self._decode_loop)

    def _pack_loop(self):
        with self._lock:
            self._ready.pop()
            self._inflight += 1

    def _decode_loop(self):
        with self._lock:
            self._inflight -= 1

    async def submit(self, batch):
        with self._lock:
            self._ready.append(batch)


class LeakyPipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self._pack = threading.Thread(target=self._pack_loop)

    def _pack_loop(self):
        self._seq += 1  # VIOLATION: unguarded vs submit's guarded bump

    async def submit(self, batch):
        with self._lock:
            self._seq += 1
