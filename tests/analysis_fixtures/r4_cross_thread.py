"""R4 fixture: unsynchronized cross-thread instance/module state. Line
numbers are asserted by tests/test_analysis.py — edit with care."""

import threading

_counter = 0


def _thread_main():
    global _counter
    _counter += 1  # VIOLATION (global: thread side), line 11


async def bump():
    global _counter
    _counter += 1  # (global: async side; thread-side line is reported)


def start():
    threading.Thread(target=_thread_main).start()


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._stopping = False
        self._thread = threading.Thread(target=self._drive)

    def _drive(self):
        while True:
            self._stopping = True  # VIOLATION line 32 (no lock, also async)
            with self._lock:
                self._items.pop()  # guarded: fine

    async def submit(self, item):
        with self._lock:
            self._items.append(item)  # guarded: fine
        self._stopping = False  # async-side mutation of the same flag


class CleanService:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._queue.clear()

    async def push(self, x):
        with self._lock:
            self._queue.append(x)
