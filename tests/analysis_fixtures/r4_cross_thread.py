"""R4 fixture: unsynchronized cross-thread instance/module state. Line
numbers are asserted by tests/test_analysis.py — edit with care."""

import threading

_counter = 0


def _thread_main():
    global _counter
    _counter += 1  # VIOLATION (global: thread side), line 11


async def bump():
    global _counter
    _counter += 1  # (global: async side; thread-side line is reported)


def start():
    threading.Thread(target=_thread_main).start()


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._stopping = False
        self._thread = threading.Thread(target=self._drive)

    def _drive(self):
        while True:
            self._stopping = True  # VIOLATION line 32 (no lock, also async)
            with self._lock:
                self._items.pop()  # guarded: fine

    async def submit(self, item):
        with self._lock:
            self._items.append(item)  # guarded: fine
        self._stopping = False  # async-side mutation of the same flag


class CleanService:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self._queue.clear()

    async def push(self, x):
        with self._lock:
            self._queue.append(x)


class PackDecodePipeline:
    """The async-dispatch handoff pattern (search/service.py
    _AsyncDispatchPipeline): a pack and a decode worker thread feed
    each other through queues while submitters park work from async
    context; every shared-state site is lock-guarded. Must be clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ready = []
        self._inflight = 0
        self._pack = threading.Thread(target=self._pack_loop)
        self._decode = threading.Thread(target=self._decode_loop)

    def _pack_loop(self):
        with self._lock:
            self._ready.pop()
            self._inflight += 1

    def _decode_loop(self):
        with self._lock:
            self._inflight -= 1

    async def submit(self, batch):
        with self._lock:
            self._ready.append(batch)


class LeakyPipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self._pack = threading.Thread(target=self._pack_loop)

    def _pack_loop(self):
        self._seq += 1  # VIOLATION: unguarded vs submit's guarded bump

    async def submit(self, batch):
        with self._lock:
            self._seq += 1


class ShardRouterPattern:
    """The placement-aware serving shape (parallel/mesh.ShardRouter +
    search/service per-shard pipelines): driver threads step a shard's
    ladder rung and re-route groups under ONE leaf lock while async
    submitters consult the same assignment map. Must be clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._assign = {0: 0}
        self._rungs = [0, 0]
        self._drive = threading.Thread(target=self._drive_loop)

    def _drive_loop(self):
        with self._lock:
            self._rungs[0] += 1  # guarded ladder step: fine
            self._assign[0] = 1  # guarded drain re-route: fine

    async def route(self, group):
        with self._lock:
            self._assign[group] = self._assign.get(group, 0)
            return self._assign[group]


class LeakyShardRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._rungs = [0, 0]
        self._drive = threading.Thread(target=self._drive_loop)

    def _drive_loop(self):
        self._rungs[0] += 1  # VIOLATION: unguarded vs degrade's bump

    async def degrade(self):
        with self._lock:
            self._rungs[0] += 1


class StripedCachePattern:
    """The process-wide eval-reuse plane (search/eval_cache.EvalCache):
    provide-time writers on driver threads and async probers share
    lock-striped buckets; every stripe access holds its stripe's lock.
    Must be clean."""

    def __init__(self):
        self._locks = [threading.Lock(), threading.Lock()]
        self._stripes = [{}, {}]
        self._drive = threading.Thread(target=self._insert_loop)

    def _insert_loop(self):
        with self._locks[0]:
            self._stripes[0][0] = 1  # guarded striped insert: fine

    async def probe(self, key):
        with self._locks[0]:
            return self._stripes[0].get(key)


class LeakyStripedCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = 0
        self._drive = threading.Thread(target=self._insert_loop)

    def _insert_loop(self):
        self._entries += 1  # VIOLATION: unguarded vs probe's guarded bump

    async def probe(self, key):
        with self._lock:
            self._entries += 1


class JournalReader:
    """`with open(...)` in a thread-bearing class: the context manager
    is a plain-Name call, not a `self.<attr>` lock — _lock_spans must
    skip it, not crash.  Guarded mutations keep the class clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._offsets = {}
        self._poller = threading.Thread(target=self._poll_loop)

    def _poll_loop(self):
        with open("/dev/null", "rb") as fh:
            data = fh.read()
        with self._lock:
            self._offsets["x"] = len(data)

    async def snapshot(self):
        with self._lock:
            return dict(self._offsets)
