"""R2 fixture: host sync reachable from jit roots. Line numbers are
asserted by tests/test_analysis.py — edit with care."""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from fishnet_tpu.utils.tracing import is_concrete


def leaf(x):
    host = np.asarray(x)  # VIOLATION line 14 (reachable via jitted_root)
    return jnp.sum(jnp.asarray(host))


def middle(x):
    if bool((x <= 0).any()):  # VIOLATION line 19 (branch on array truth)
        return leaf(x)
    return x * 2


@jax.jit
def jitted_root(x):
    v = x.item()  # VIOLATION line 26 (.item in jit root)
    return middle(x) + v


def assigned_root(x):
    return float(x) + 1.0  # VIOLATION line 31 (float() on traced value)


assigned_jit = jax.jit(functools.partial(assigned_root))


def guarded(x):
    if is_concrete(x):
        # Host-only fast path: exempt by the concreteness guard.
        if bool((np.asarray(x) <= 0).any()):
            raise ValueError("negative")
    return x * 3


guarded_jit = jax.jit(guarded)


def never_traced(x):
    # Not reachable from any jit root: host code may sync freely.
    return np.asarray(x).item()


def static_ok(x):
    n = int(x.shape[0])  # static under tracing: exempt
    return jnp.zeros((n,))


static_jit = jax.jit(static_ok)


# -- fused-PSQT kernel shape (ops/ft_gather.py): host syncs reachable
# only through a lambda argument and pl.when-decorated nested defs —
# the call-graph edges added for the fused PSQT path.
from jax.experimental import pallas as pl  # noqa: E402


def _psqt_kernel(idx_ref, pout_ref, *, with_psqt):
    def transfer(k):
        return np.asarray(idx_ref)  # VIOLATION line 69 (lambda edge)

    def both_modes(fn):
        return fn(0)

    @pl.when(with_psqt)
    def _():
        pout_ref[0] = np.asarray(idx_ref).sum()  # VIOLATION line 76

    @pl.when(not with_psqt)
    def _():
        host = np.asarray(pout_ref)  # VIOLATION line 80 (2nd `_` def)
        return host

    return both_modes(lambda k: transfer(k))


fused_psqt = pl.pallas_call(
    functools.partial(_psqt_kernel, with_psqt=True),
    out_shape=None,
)
