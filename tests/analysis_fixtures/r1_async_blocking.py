"""R1 fixture: blocking calls inside async bodies. Line numbers are
asserted by tests/test_analysis.py — edit with care."""

import asyncio
import subprocess
import time
from time import sleep as zzz

import requests


async def bad_sleep():
    time.sleep(1.0)  # VIOLATION line 13


async def bad_alias_sleep():
    zzz(0.5)  # VIOLATION line 17


async def bad_subprocess():
    subprocess.run(["true"])  # VIOLATION line 21


async def bad_requests():
    return requests.get("http://example.invalid")  # VIOLATION line 25


async def bad_communicate(proc):
    out, err = proc.communicate()  # VIOLATION line 29
    return out


async def fine():
    await asyncio.sleep(1.0)
    proc = await asyncio.create_subprocess_exec("true")
    await proc.communicate()  # awaited: asyncio subprocess, fine
    # Shipping the blocking callable off-loop is the sanctioned pattern:
    await asyncio.to_thread(time.sleep, 0.1)

    def helper():
        time.sleep(1.0)  # sync nested def: runs in an executor, fine

    return helper


def sync_caller():
    time.sleep(1.0)  # not async: fine
