"""R9 fixture: use-after-donation of donate_argnums buffers. Line
numbers are asserted by tests/test_analysis.py — edit with care."""

import functools

import jax


def _step(state, batch):
    return state


step_jit = jax.jit(_step, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(1,))
def fwd(params, buf):
    return buf


def train_bad(state, batch):
    out = step_jit(state, batch)
    return state.params, out  # VIOLATION line 23: `state` donated on 22


def train_good(state, batch):
    state = step_jit(state, batch)  # classic ping-pong rebind: fine
    return state.params


def fwd_bad(params, buf):
    out = fwd(params, buf)
    return buf + out  # VIOLATION line 33: `buf` donated on 32


class Runner:
    def __init__(self):
        self._fj = jax.jit(self._f, donate_argnums=(0,))
        self._buf = None

    def _f(self, b):
        return b

    def run_bad(self):
        out = self._fj(self._buf)
        return self._buf, out  # VIOLATION line 46: `self._buf` donated on 45

    def run_good(self):
        self._buf = self._fj(self._buf)  # rebind from the result: fine
        return self._buf
