"""R8 fixture: escape hatches missing from the knob registry. The test
harness runs EscapeHatchRule with an explicit declared-knob list that
covers only FISHNET_FIXTURE_DECLARED and --fixture-declared. Line
numbers are asserted by tests/test_analysis.py — edit with care."""

import os

DECLARED = os.environ.get("FISHNET_FIXTURE_DECLARED")  # declared: fine

# VIOLATION line 11: env read with no registry row.
UNDECLARED = os.environ.get("FISHNET_FIXTURE_UNDECLARED", "0")

# VIOLATION line 14: name-constant env read with no registry row.
ROGUE_ENV = "FISHNET_FIXTURE_ROGUE"


def hatch():
    return os.environ.get(ROGUE_ENV)


def build_parser(parser):
    parser.add_argument("--fixture-declared", type=int)  # declared: fine
    # VIOLATION line 24: CLI option with no registry row.
    parser.add_argument("--fixture-undeclared", action="store_true")
