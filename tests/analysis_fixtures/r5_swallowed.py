"""R5 fixture: swallowed-exception violations at known lines."""
import asyncio

from fishnet_tpu import telemetry

ERRORS = telemetry.REGISTRY.counter("fx_errors_total", "fixture")


def swallow_bare():
    try:
        risky()
    except:  # line 12: bare except, pass-only
        pass


def swallow_broad_logged(logger):
    try:
        risky()
    except Exception as err:  # line 19: log-only is NOT observable
        logger.error(f"oops: {err!r}")


def swallow_tuple():
    try:
        risky()
    except (ValueError, BaseException):  # line 26: broad via tuple
        return None


def handled_reraise():
    try:
        risky()
    except Exception:
        raise


def handled_counter():
    try:
        risky()
    except Exception:
        ERRORS.inc()


def handled_return_err():
    try:
        risky()
    except Exception as err:
        return err


def handled_future(fut):
    try:
        risky()
    except Exception as err:
        fut.set_exception(err)


def handled_narrow():
    try:
        risky()
    except ValueError:
        pass  # narrow: catching what you expect is handling


def risky():
    raise ValueError("boom")
