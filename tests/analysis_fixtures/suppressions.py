"""Suppression fixture. Line numbers are asserted by
tests/test_analysis.py — edit with care."""

import time


async def justified():
    # One-shot startup script, loop idle by construction here:
    time.sleep(0.1)  # fishnet: ignore[R1] -- startup path, loop not serving yet


async def unjustified():
    time.sleep(0.1)  # fishnet: ignore[R1]


async def wrong_rule():
    time.sleep(0.1)  # fishnet: ignore[R2] -- suppresses the wrong rule
