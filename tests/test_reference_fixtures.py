"""Pinned cross-engine parity fixtures (VERDICT r4 item 7).

Every other parity suite in this repo is SELF-referential (scalar
backend vs batched backend of the same search). These fixtures pin the
search against EXTERNALLY published analysis: famous games and classic
tactics-suite positions whose best move is not in dispute — Morphy's
Opera game queen sacrifice, Réti–Tartakower's Qd8+!!, Win-At-Chess
test-suite material shots. A search quality regression (ordering bug,
over-aggressive pruning tier, broken mate scoring) fails here even when
both backends regress identically, which is exactly the blind spot of
the self-referential suites (BASELINE.json's north star is parity vs
stock Stockfish; with zero egress these published solutions are the
strongest available proxy).

Mate fixtures must report the exact mate distance (objectively
checkable by our own movegen); material fixtures must play the
published move. The node budget is protocol-realistic but small enough
for CI (the material net at 200k nodes reaches depth ~14-16).
"""

import pytest

from fishnet_tpu.chess import Board
from fishnet_tpu.search.service import SearchService
from tests.test_search import material_net

pytestmark = pytest.mark.anyio

# (name, fen, best move uci, mate-in-moves or None)
MATE_FIXTURES = [
    # Morphy vs Duke Karl / Count Isouard, Paris Opera 1858: 16.Qb8+!!
    # Nxb8 17.Rd8#. The most-published mate-in-2 in chess literature.
    (
        "opera-game-qb8",
        "4kb1r/p2n1ppp/4q3/4p1B1/4P3/1Q6/PPP2PPP/2KR4 w k - 0 16",
        "b3b8",
        2,
    ),
    # Réti vs Tartakower, Vienna 1910: 9.Qd8+!! Kxd8 10.Bg5+ (double
    # check) and 11.Bd8# / Rd8# — mate in 3 either way.
    (
        "reti-tartakower-qd8",
        "rnb1kb1r/pp3ppp/2p5/4q3/4n3/3Q4/PPPB1PPP/2KR1BNR w kq - 0 9",
        "d3d8",
        3,
    ),
    # The textbook two-rook mate: Ra7 seals the seventh rank, Rb8# is
    # the unique fastest mate (a8-check instead lets the king out).
    (
        "two-rook-mate",
        "6k1/R7/1R6/8/8/8/8/6K1 w - - 0 1",
        "b6b8",
        1,
    ),
]

MATERIAL_FIXTURES = [
    # WAC.001: 1.Qg6! and the threats on h6/h7 win decisive material
    # (fxg6 loses to Nxg6#; the suite's published key move).
    (
        "wac-001-qg6",
        "2rr3k/pp3pp1/1nnqbN1p/3pN3/2pP4/2P3Q1/PPB4P/R4RK1 w - - 0 1",
        "g3g6",
    ),
    # WAC.002 (Win At Chess, Reinfeld): 1...Rxb2 wins the b-pawn with
    # a dominating rook — the published solution move.
    (
        "wac-002-rxb2",
        "8/7p/5k2/5p2/p1p2P2/Pr1pPK2/1P1R3P/8 b - - 0 1",
        "b3b2",
    ),
    # WAC.004: 1.Qxh7+! Kxh7 forced, and White's attack recoups with
    # decisive material (the suite's published key move).
    (
        "wac-004-qxh7",
        "r1bq2rk/pp3pbp/2p1p1pQ/7P/3P4/2PB1N2/PP3PPR/2KR4 w - - 0 1",
        "h6h7",
    ),
]


@pytest.fixture(scope="module")
def service():
    svc = SearchService(
        weights=material_net(),
        pool_slots=8,
        batch_capacity=64,
        tt_bytes=128 << 20,
        backend="scalar",
    )
    yield svc
    svc.close()


async def test_fixture_positions_are_legal():
    """The pinned FENs themselves parse and the pinned moves are legal —
    guards against fixture typos independently of search strength."""
    for name, fen, bm, _ in MATE_FIXTURES:
        board = Board(fen)
        assert bm in board.legal_moves(), f"{name}: {bm} not legal in {fen}"
    for name, fen, bm in MATERIAL_FIXTURES:
        board = Board(fen)
        assert bm in board.legal_moves(), f"{name}: {bm} not legal"


async def test_published_mates_found(service):
    """Each historical mate must be found with the exact published move
    AND the exact mate distance — no tolerance: these are forced."""
    for name, fen, bm, mate_in in MATE_FIXTURES:
        res = await service.search(fen, [], nodes=200_000, depth=12)
        assert res.best_move == bm, (
            f"{name}: played {res.best_move}, published {bm}"
        )
        final = [l for l in res.lines if l.multipv == 1][-1]
        assert final.is_mate and final.value == mate_in, (
            f"{name}: scored {final.value} (mate={final.is_mate}), "
            f"published mate in {mate_in}"
        )


async def test_published_material_shots_found(service):
    """The WAC shots: at least one published key move must be played.
    The bar is deliberately lower than the mate fixtures' (which demand
    exactness): the test net is MATERIAL-ONLY, and two of these
    positions reward attacking resources a material eval legitimately
    trades against other material-sound moves (measured: it finds
    Qxh7+, prefers Ne8/c3 over Qg6/Rxb2). Zero hits would mean the
    search itself stopped seeing published tactics — the regression
    this guards. A real NNUE net tightens this to all-of-N."""
    hits = []
    for name, fen, bm in MATERIAL_FIXTURES:
        res = await service.search(fen, [], nodes=200_000)
        if res.best_move == bm:
            hits.append(name)
    assert hits, "search found NONE of the published key moves"
