"""Multi-threaded host scheduling (VERDICT r3 #1): N driver threads
stepping disjoint slot groups of one shared pool, sharing the lockless
XOR-validated transposition table and the device evaluator.

The reference's host parallelism is one single-threaded engine process
per core (src/main.rs:158-170); these tests pin the capability that
replaces it — and that the shared-state surfaces (TT, counters, AIMD
budget, stop/abort latches) stay correct under concurrency."""

import asyncio

import pytest

from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import SearchService

pytestmark = pytest.mark.anyio

FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R w KQkq - 4 4",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
    "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
]


def _service(threads, backend="jax", **kw):
    kw.setdefault("pool_slots", 64)
    kw.setdefault("batch_capacity", 64)
    kw.setdefault("tt_bytes", 16 << 20)
    return SearchService(
        weights=NnueWeights.random(seed=3), backend=backend,
        driver_threads=threads, **kw
    )


async def test_concurrent_searches_two_threads():
    svc = _service(2)
    try:
        assert svc.driver_threads == 2
        results = await asyncio.gather(
            *[svc.search(f, [], nodes=500) for f in FENS * 6]
        )
        assert len(results) == 30
        for res in results:
            assert res.best_move is not None
            assert res.nodes > 0
    finally:
        svc.close()


async def _thread_determinism_sweep(fens):
    """Thread-count must not change WHAT a search computes, only where
    it runs: identical submissions, sequentially awaited (so the shared
    TT evolves deterministically), give identical scores/moves for 1 and
    2 driver threads."""
    outs = {}
    for threads in (1, 2):
        svc = _service(threads, tt_bytes=64 << 20)
        svc.set_prefetch(8, adaptive=False)
        try:
            out = []
            for fen in fens:
                r = await svc.search(fen, [], depth=4)
                line = [l for l in r.lines if l.multipv == 1][-1]
                out.append((line.value, line.is_mate, r.best_move))
            outs[threads] = out
        finally:
            svc.close()
    assert outs[1] == outs[2]


async def test_two_threads_match_one_thread_results():
    # Commit-gate smoke (3 positions); the full set incl. the promotion
    # tactic and the kiwipete middlegame runs in the slow venue below.
    await _thread_determinism_sweep(FENS[:3])


@pytest.mark.slow
async def test_two_threads_match_one_thread_results_full():
    await _thread_determinism_sweep(FENS)


async def test_shared_tt_thrash_across_threads():
    """Many fibers on different threads searching the SAME position:
    maximal TT write contention on identical clusters. The lockless
    XOR validation must never surface a torn entry as a wrong score —
    every search of the same position with the same budget must agree
    with the single-threaded answer."""
    svc = _service(4, pool_slots=128)
    try:
        fen = FENS[1]
        results = await asyncio.gather(
            *[svc.search(fen, [], nodes=800) for _ in range(48)]
        )
        moves = {r.best_move for r in results}
        assert all(r.best_move for r in results)
        # All searches see the same position and (depth-1-complete)
        # budget; sharing the TT may deepen later ones but the move set
        # must stay within this position's legal moves.
        from fishnet_tpu.chess import Board

        legal = set(Board(fen).legal_moves())
        assert moves <= legal
    finally:
        svc.close()


async def test_multithread_variant_and_standard_mix():
    from fishnet_tpu.protocol.types import Variant

    svc = _service(2)
    try:
        tasks = [svc.search(FENS[0], [], nodes=400) for _ in range(6)]
        tasks += [
            svc.search(
                "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w - - 0 1",
                [], depth=3, variant=Variant.ANTICHESS,
            )
            for _ in range(6)
        ]
        results = await asyncio.gather(*tasks)
        assert all(r.best_move for r in results)
    finally:
        svc.close()


async def test_movetime_stop_unsticks_blocked_driver():
    """A scalar search never suspends, so its driver thread is BLOCKED
    inside fc_pool_step for the search's whole life — the movetime
    watchdog must stop it from the event-loop thread directly (routing
    the stop through the stuck driver's loop would deadlock; this was
    latent even single-threaded)."""
    svc = _service(2, backend="scalar")
    try:
        res = await asyncio.wait_for(
            svc.search(FENS[4], [], movetime_seconds=0.3), timeout=30
        )
        assert res.best_move is not None  # partial result, not an error
    finally:
        svc.close()


async def test_close_unwinds_all_threads():
    svc = _service(3)
    tasks = [
        asyncio.create_task(svc.search(f, [], nodes=10_000_000))
        for f in FENS * 3
    ]
    await asyncio.sleep(1.0)
    svc.close()
    done = await asyncio.gather(*tasks, return_exceptions=True)
    # Every future resolves (result or service-shutdown error); none hang.
    assert len(done) == 15
    assert not svc.is_alive()


async def test_cancellation_with_threads():
    svc = _service(2)
    try:
        tasks = [
            asyncio.create_task(svc.search(f, [], nodes=5_000_000))
            for f in FENS
        ]
        await asyncio.sleep(0.5)
        for t in tasks:
            t.cancel()
        done = await asyncio.gather(*tasks, return_exceptions=True)
        assert all(isinstance(r, asyncio.CancelledError) for r in done)
        # Slots freed: a fresh search still completes.
        res = await svc.search(FENS[0], [], nodes=500)
        assert res.best_move
    finally:
        svc.close()
