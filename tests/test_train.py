"""Training subsystem: float model, sharded train step, quantization
export consistency with the integer serving path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights
from fishnet_tpu.parallel.mesh import factor_mesh, make_mesh
from fishnet_tpu.train import NetConfig, Trainer, forward, init_params, quantize
from fishnet_tpu.train.model import NNUE2SCORE

TINY = NetConfig(num_features=256, max_active=8, l1=32, l2=15, l3=32)


def fake_batch(rng, n, cfg):
    indices = np.full((n, 2, cfg.max_active), cfg.num_features, dtype=np.int32)
    for b in range(n):
        k = int(rng.integers(2, cfg.max_active + 1))
        for p in range(2):
            indices[b, p, :k] = np.sort(rng.choice(cfg.num_features, k, replace=False))
    return {
        "indices": jnp.asarray(indices),
        "buckets": jnp.asarray(rng.integers(0, 8, n, dtype=np.int32)),
        "score_cp": jnp.asarray(rng.normal(0, 150, n).astype(np.float32)),
        "outcome": jnp.asarray(rng.choice([0.0, 0.5, 1.0], n).astype(np.float32)),
    }


def test_forward_shapes_and_padding():
    params = init_params(jax.random.PRNGKey(0), TINY)
    rng = np.random.default_rng(0)
    batch = fake_batch(rng, 4, TINY)
    out = forward(params, batch["indices"], batch["buckets"], TINY)
    assert out.shape == (4,)
    assert np.all(np.isfinite(np.asarray(out)))

    # Sentinel-padded slots are no-ops: adding extra padding cannot
    # change the output.
    idx2 = np.asarray(batch["indices"]).copy()
    out2 = forward(params, jnp.asarray(idx2), batch["buckets"], TINY)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-6)


def test_train_step_reduces_loss_single_device():
    trainer = Trainer(cfg=TINY, learning_rate=5e-3)
    state = trainer.init(seed=0)
    rng = np.random.default_rng(1)
    batch = fake_batch(rng, 128, TINY)
    losses = []
    for _ in range(30):
        state, metrics = trainer.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]
    assert int(state.step) == 30


def test_train_step_sharded_matches_single_device():
    mesh = make_mesh()  # 8 virtual CPU devices from conftest
    assert mesh.devices.size == 8
    cfg = NetConfig(num_features=256, max_active=8, l1=64, l2=15, l3=32)

    rng = np.random.default_rng(2)
    batch = fake_batch(rng, 64, cfg)

    t_single = Trainer(cfg=cfg, learning_rate=1e-3)
    t_shard = Trainer(cfg=cfg, mesh=mesh, learning_rate=1e-3)
    s_single = t_single.init(seed=3)
    s_shard = t_shard.init(seed=3)

    for _ in range(3):
        s_single, m_single = t_single.step(s_single, batch)
        s_shard, m_shard = t_shard.step(s_shard, batch)

    np.testing.assert_allclose(
        float(m_single["loss"]), float(m_shard["loss"]), rtol=1e-4
    )
    for key in s_single.params:
        np.testing.assert_allclose(
            np.asarray(s_single.params[key]),
            np.asarray(s_shard.params[key]),
            rtol=2e-4,
            atol=2e-6,
            err_msg=key,
        )


def test_factor_mesh():
    assert factor_mesh(8) == (4, 2)
    assert factor_mesh(1) == (1, 1)
    assert factor_mesh(7) == (7, 1)
    assert factor_mesh(4, max_model=4) == (1, 4)


@pytest.mark.slow
def test_quantize_roundtrip_tracks_float():
    """Quantized integer eval of exported weights tracks the float model
    on full-spec shapes. With random (untrained) weights int8 rounding
    noise accumulates across the 1024-wide l1 contraction, so the bound
    is statistical: high correlation and modest mean error. (Trained
    nets, whose weights co-adapt to the grid via clip_params, sit much
    tighter.)"""
    cfg = NetConfig()
    params = init_params(jax.random.PRNGKey(4), cfg)
    params["ft_psqt"] = (
        jax.random.normal(jax.random.PRNGKey(5), params["ft_psqt"].shape) * 0.02
    )
    weights = quantize(params, cfg)
    qparams = params_from_weights(weights)

    rng = np.random.default_rng(5)
    n = 32
    indices = np.full((n, 2, cfg.max_active), cfg.num_features, dtype=np.int32)
    for b in range(n):
        k = int(rng.integers(8, cfg.max_active + 1))
        for p in range(2):
            indices[b, p, :k] = np.sort(rng.choice(cfg.num_features, k, replace=False))
    buckets = rng.integers(0, 8, n, dtype=np.int32)

    float_cp = np.asarray(
        forward(params, jnp.asarray(indices), jnp.asarray(buckets), cfg)
    ) * NNUE2SCORE
    # Integer path pads with NUM_FEATURES sentinel too.
    int_cp = np.asarray(
        evaluate_batch_jit(qparams, jnp.asarray(indices), jnp.asarray(buckets))
    )
    err = np.abs(float_cp - int_cp)
    corr = np.corrcoef(float_cp, int_cp)[0, 1]
    # Slope ~1 catches any scale-wiring bug (e.g. a wrong psqt or output
    # export scale); corr/mean bound the rounding noise.
    slope = float(np.polyfit(float_cp, int_cp, 1)[0])
    assert 0.8 <= slope <= 1.25, slope
    assert corr > 0.95, (corr, float_cp[:5], int_cp[:5])
    assert float(err.mean()) <= 60.0, err.mean()
