"""Sharded evaluator: multi-device integer eval must be bit-identical to
the single-device jit."""

import jax.numpy as jnp
import numpy as np

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.parallel.mesh import ShardedEvaluator, make_mesh


def test_sharded_eval_matches_single_device():
    weights = NnueWeights.random(seed=11)
    params = params_from_weights(weights)
    mesh = make_mesh()
    evaluator = ShardedEvaluator(params, mesh=mesh, batch_capacity=64)
    assert evaluator.batch_capacity % mesh.devices.size == 0

    rng = np.random.default_rng(3)
    n = evaluator.batch_capacity
    indices = np.full((n, 2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES, np.int32)
    for b in range(n):
        k = int(rng.integers(4, spec.MAX_ACTIVE_FEATURES + 1))
        for p in range(2):
            indices[b, p, :k] = np.sort(
                rng.choice(spec.NUM_FEATURES, k, replace=False)
            )
    buckets = rng.integers(0, 8, n, dtype=np.int32)

    sharded = np.asarray(evaluator(None, jnp.asarray(indices), jnp.asarray(buckets)))
    single = np.asarray(evaluate_batch_jit(params, jnp.asarray(indices), jnp.asarray(buckets)))
    np.testing.assert_array_equal(sharded, single)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    import jax

    out = np.asarray(jax.jit(fn)(*args))
    assert out.shape == (64,)
    ge.dryrun_multichip(8)
