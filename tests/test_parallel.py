"""Sharded evaluator: multi-device integer eval must be bit-identical to
the single-device jit."""

import jax.numpy as jnp
import numpy as np

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.parallel.mesh import ShardedEvaluator, make_mesh


def test_sharded_eval_matches_single_device():
    weights = NnueWeights.random(seed=11)
    params = params_from_weights(weights)
    mesh = make_mesh()
    evaluator = ShardedEvaluator(params, mesh=mesh, batch_capacity=64)
    assert evaluator.batch_capacity % mesh.devices.size == 0

    rng = np.random.default_rng(3)
    n = evaluator.batch_capacity
    indices = np.full((n, 2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES, np.int32)
    for b in range(n):
        k = int(rng.integers(4, spec.MAX_ACTIVE_FEATURES + 1))
        for p in range(2):
            indices[b, p, :k] = np.sort(
                rng.choice(spec.NUM_FEATURES, k, replace=False)
            )
    buckets = rng.integers(0, 8, n, dtype=np.int32)

    sharded = np.asarray(evaluator(None, jnp.asarray(indices), jnp.asarray(buckets)))
    single = np.asarray(evaluate_batch_jit(params, jnp.asarray(indices), jnp.asarray(buckets)))
    np.testing.assert_array_equal(sharded, single)


def test_sharded_eval_compiles_without_collectives():
    """VERDICT r2 weak #5: GSPMD resolved cross-shard delta references
    with an all-gather of the [B, 2, 1024] int32 accumulators (~134 MB
    per 16k step over ICI). The shard_map formulation plus the pool's
    shard-aligned block emission make the compiled program collective-
    free BY CONSTRUCTION — pinned here against the HLO text."""
    params = params_from_weights(NnueWeights.random(seed=11))
    evaluator = ShardedEvaluator(params, mesh=make_mesh(), batch_capacity=64)
    n = evaluator.batch_capacity
    indices = np.full(
        (n, 2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES, np.uint16
    )
    buckets = np.zeros((n,), np.int32)
    parent = np.full((n,), -1, np.int32)
    material = np.zeros((n,), np.int32)
    hlo = (
        evaluator._fn_mat.lower(
            evaluator.params, indices, buckets, parent, material
        )
        .compile()
        .as_text()
    )
    for collective in (
        "all-gather", "all-reduce", "all-to-all", "collective-permute",
        "ragged-all-to-all",
    ):
        assert collective not in hlo, f"sharded eval emits {collective}"


def test_sharded_delta_blocks_match_single_device():
    """Shard-aligned incremental blocks (the production wire shape) must
    evaluate bit-identically sharded and single-device: the evaluator
    rebases anchor codes shard-locally and every anchor lives in the
    same shard as its children (the pool's emit alignment guarantees
    it; a cross-shard reference raises)."""
    import pytest
    from test_ops import _block_batch

    params = params_from_weights(NnueWeights.random(seed=19))
    mesh = make_mesh()
    evaluator = ShardedEvaluator(params, mesh=mesh, batch_capacity=64)
    n = evaluator.batch_capacity
    n_dev = mesh.devices.size
    shard = n // n_dev
    rng = np.random.default_rng(7)
    # One block per shard: every delta's anchor is its shard's entry 0.
    idx, parent, _ = _block_batch(
        spec.NUM_FEATURES, spec.MAX_ACTIVE_FEATURES, n // shard, shard, rng
    )
    buckets = rng.integers(0, 8, n).astype(np.int32)
    sharded = np.asarray(
        evaluator(None, np.asarray(idx), buckets, np.asarray(parent))
    )
    single = np.asarray(
        evaluate_batch_jit(params, idx, jnp.asarray(buckets), parent)
    )
    np.testing.assert_array_equal(sharded, single)

    # A cross-shard reference must be rejected loudly, not silently
    # resolved against the wrong shard's accumulator.
    bad = np.asarray(parent).copy()
    bad[shard + 1] = 0 << 1  # second shard's child anchored in the first
    with pytest.raises(ValueError, match="outside its mesh shard"):
        evaluator(None, np.asarray(idx), buckets, bad)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    import jax

    out = np.asarray(jax.jit(fn)(*args))
    assert out.shape == (64,)
    ge.dryrun_multichip(8)


def test_sharded_service_rounds_buckets_to_shard_multiple():
    """Every eval-size bucket (and the capacities) must split evenly
    across the mesh, or the sharded jit would reject the batch shape."""
    from fishnet_tpu.search.service import SearchService

    weights = NnueWeights.random(seed=5)
    evaluator = ShardedEvaluator(
        params_from_weights(weights), mesh=make_mesh(), batch_capacity=64
    )
    svc = SearchService(
        weights=weights,
        pool_slots=16,
        batch_capacity=100,  # deliberately not a multiple of 8
        tt_bytes=4 << 20,
        evaluator=evaluator,
        eval_sizes=(50, 100),
    )
    try:
        n_dev = evaluator.size_multiple
        assert svc.batch_capacity % n_dev == 0
        assert svc._group_capacity % n_dev == 0
        assert all(s % n_dev == 0 for s in svc._eval_sizes)
    finally:
        svc.close()


async def test_client_e2e_on_sharded_path(anyio_backend):
    """The multi-chip serving slice: fake lichess server -> Client ->
    workers -> shared SearchService whose leaf microbatches are sharded
    over the 8-device mesh (VERDICT round 1: serving must not hardcode
    the single-device evaluator)."""
    import asyncio

    from fishnet_tpu.client import Client
    from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
    from fishnet_tpu.search.service import SearchService
    from fishnet_tpu.utils.logger import Logger
    from tests.fake_server import VALID_KEY, FakeServer

    weights = NnueWeights.random(seed=11)
    evaluator = ShardedEvaluator(
        params_from_weights(weights), mesh=make_mesh(), batch_capacity=64
    )
    service = SearchService(
        weights=weights,
        pool_slots=64,
        batch_capacity=64,
        tt_bytes=16 << 20,
        evaluator=evaluator,
    )
    try:
        async with FakeServer() as server:
            work_id = server.lichess.add_analysis_job(
                moves="e2e4 c7c5 g1f3", nodes=300
            )
            client = Client(
                endpoint=server.endpoint,
                key=VALID_KEY,
                cores=2,
                engine_factory=TpuNnueEngineFactory(service),
                logger=Logger(),
                max_backoff=0.2,
            )
            await client.start()
            deadline = asyncio.get_running_loop().time() + 120.0
            while asyncio.get_running_loop().time() < deadline:
                if work_id in server.lichess.analyses:
                    break
                await asyncio.sleep(0.05)
            await client.stop()
            assert work_id in server.lichess.analyses, (
                "analysis not completed within deadline on the sharded path"
            )
            parts = server.lichess.analyses[work_id]["analysis"]
            assert len(parts) == 4
            for part in parts:
                assert "score" in part
                assert part["nodes"] >= 1
    finally:
        service.close()


async def test_sharded_packed_search_parity(anyio_backend):
    """The sharded PACKED wire (service-side per-shard repack +
    on-device expansion inside the shard_map) must reproduce the
    single-device backend's search results exactly — scores, mate
    flags, and best moves, position by position. Sequential submission
    + pinned prefetch, like every cross-backend parity suite (the TT
    evolution must be a deterministic function of the sequence)."""
    from fishnet_tpu.search.service import SearchService
    from tests.test_search import _parity_results, _random_fens

    weights = NnueWeights.random(seed=23)
    fens = _random_fens(10, seed=123)

    single = await _parity_results("jax", weights, fens, depth=3, prefetch=4)

    evaluator = ShardedEvaluator(
        params_from_weights(weights), mesh=make_mesh(), batch_capacity=64
    )
    svc = SearchService(
        weights=weights, pool_slots=16, batch_capacity=64,
        tt_bytes=64 << 20, evaluator=evaluator,
    )
    svc.set_prefetch(4, adaptive=False)
    try:
        assert svc._sharded_packed, "mesh path fell back to dense wire"
        sharded = []
        for fen in fens:
            r = await svc.search(fen, [], depth=3)
            line = [l for l in r.lines if l.multipv == 1][-1]
            sharded.append((line.value, line.is_mate, r.best_move))
    finally:
        svc.close()
    mismatches = [
        (fen, s, j) for fen, s, j in zip(fens, single, sharded) if s != j
    ]
    assert not mismatches, (
        f"{len(mismatches)} of {len(fens)} diverged; first: {mismatches[0]}"
    )
