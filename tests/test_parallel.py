"""Sharded evaluator: multi-device integer eval must be bit-identical to
the single-device jit."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.parallel.mesh import ShardedEvaluator, make_mesh


def test_sharded_eval_matches_single_device():
    weights = NnueWeights.random(seed=11)
    params = params_from_weights(weights)
    mesh = make_mesh()
    evaluator = ShardedEvaluator(params, mesh=mesh, batch_capacity=64)
    assert evaluator.batch_capacity % mesh.devices.size == 0

    rng = np.random.default_rng(3)
    n = evaluator.batch_capacity
    indices = np.full((n, 2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES, np.int32)
    for b in range(n):
        k = int(rng.integers(4, spec.MAX_ACTIVE_FEATURES + 1))
        for p in range(2):
            indices[b, p, :k] = np.sort(
                rng.choice(spec.NUM_FEATURES, k, replace=False)
            )
    buckets = rng.integers(0, 8, n, dtype=np.int32)

    sharded = np.asarray(evaluator(None, jnp.asarray(indices), jnp.asarray(buckets)))
    single = np.asarray(evaluate_batch_jit(params, jnp.asarray(indices), jnp.asarray(buckets)))
    np.testing.assert_array_equal(sharded, single)


def test_sharded_eval_compiles_without_collectives():
    """VERDICT r2 weak #5: GSPMD resolved cross-shard delta references
    with an all-gather of the [B, 2, 1024] int32 accumulators (~134 MB
    per 16k step over ICI). The shard_map formulation plus the pool's
    shard-aligned block emission make the compiled program collective-
    free BY CONSTRUCTION — pinned here against the HLO text."""
    params = params_from_weights(NnueWeights.random(seed=11))
    evaluator = ShardedEvaluator(params, mesh=make_mesh(), batch_capacity=64)
    n = evaluator.batch_capacity
    indices = np.full(
        (n, 2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES, np.uint16
    )
    buckets = np.zeros((n,), np.int32)
    parent = np.full((n,), -1, np.int32)
    material = np.zeros((n,), np.int32)
    hlo = (
        evaluator._fn_mat.lower(
            evaluator.params, indices, buckets, parent, material
        )
        .compile()
        .as_text()
    )
    for collective in (
        "all-gather", "all-reduce", "all-to-all", "collective-permute",
        "ragged-all-to-all",
    ):
        assert collective not in hlo, f"sharded eval emits {collective}"


def test_sharded_delta_blocks_match_single_device():
    """Shard-aligned incremental blocks (the production wire shape) must
    evaluate bit-identically sharded and single-device: the evaluator
    rebases anchor codes shard-locally and every anchor lives in the
    same shard as its children (the pool's emit alignment guarantees
    it; a cross-shard reference raises)."""
    import pytest
    from test_ops import _block_batch

    params = params_from_weights(NnueWeights.random(seed=19))
    mesh = make_mesh()
    evaluator = ShardedEvaluator(params, mesh=mesh, batch_capacity=64)
    n = evaluator.batch_capacity
    n_dev = mesh.devices.size
    shard = n // n_dev
    rng = np.random.default_rng(7)
    # One block per shard: every delta's anchor is its shard's entry 0.
    idx, parent, _ = _block_batch(
        spec.NUM_FEATURES, spec.MAX_ACTIVE_FEATURES, n // shard, shard, rng
    )
    buckets = rng.integers(0, 8, n).astype(np.int32)
    sharded = np.asarray(
        evaluator(None, np.asarray(idx), buckets, np.asarray(parent))
    )
    single = np.asarray(
        evaluate_batch_jit(params, idx, jnp.asarray(buckets), parent)
    )
    np.testing.assert_array_equal(sharded, single)

    # A cross-shard reference must be rejected loudly, not silently
    # resolved against the wrong shard's accumulator.
    bad = np.asarray(parent).copy()
    bad[shard + 1] = 0 << 1  # second shard's child anchored in the first
    with pytest.raises(ValueError, match="outside its mesh shard"):
        evaluator(None, np.asarray(idx), buckets, bad)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    import jax

    out = np.asarray(jax.jit(fn)(*args))
    assert out.shape == (64,)
    ge.dryrun_multichip(8)


def test_sharded_service_rounds_buckets_to_shard_multiple():
    """Every eval-size bucket (and the capacities) must split evenly
    across the mesh, or the sharded jit would reject the batch shape."""
    from fishnet_tpu.search.service import SearchService

    weights = NnueWeights.random(seed=5)
    evaluator = ShardedEvaluator(
        params_from_weights(weights), mesh=make_mesh(), batch_capacity=64
    )
    svc = SearchService(
        weights=weights,
        pool_slots=16,
        batch_capacity=100,  # deliberately not a multiple of 8
        tt_bytes=4 << 20,
        evaluator=evaluator,
        eval_sizes=(50, 100),
    )
    try:
        n_dev = evaluator.size_multiple
        assert svc.batch_capacity % n_dev == 0
        assert svc._group_capacity % n_dev == 0
        assert all(s % n_dev == 0 for s in svc._eval_sizes)
    finally:
        svc.close()


async def test_client_e2e_on_sharded_path(anyio_backend):
    """The multi-chip serving slice: fake lichess server -> Client ->
    workers -> shared SearchService whose leaf microbatches are sharded
    over the 8-device mesh (VERDICT round 1: serving must not hardcode
    the single-device evaluator)."""
    import asyncio

    from fishnet_tpu.client import Client
    from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
    from fishnet_tpu.search.service import SearchService
    from fishnet_tpu.utils.logger import Logger
    from tests.fake_server import VALID_KEY, FakeServer

    weights = NnueWeights.random(seed=11)
    evaluator = ShardedEvaluator(
        params_from_weights(weights), mesh=make_mesh(), batch_capacity=64
    )
    service = SearchService(
        weights=weights,
        pool_slots=64,
        batch_capacity=64,
        tt_bytes=16 << 20,
        evaluator=evaluator,
    )
    try:
        async with FakeServer() as server:
            work_id = server.lichess.add_analysis_job(
                moves="e2e4 c7c5 g1f3", nodes=300
            )
            client = Client(
                endpoint=server.endpoint,
                key=VALID_KEY,
                cores=2,
                engine_factory=TpuNnueEngineFactory(service),
                logger=Logger(),
                max_backoff=0.2,
            )
            await client.start()
            deadline = asyncio.get_running_loop().time() + 120.0
            while asyncio.get_running_loop().time() < deadline:
                if work_id in server.lichess.analyses:
                    break
                await asyncio.sleep(0.05)
            await client.stop()
            assert work_id in server.lichess.analyses, (
                "analysis not completed within deadline on the sharded path"
            )
            parts = server.lichess.analyses[work_id]["analysis"]
            assert len(parts) == 4
            for part in parts:
                assert "score" in part
                assert part["nodes"] >= 1
    finally:
        service.close()


# ---------------------------------------------------------------------------
# Placement-aware serving mesh (doc/sharding.md): shard router units,
# per-shard segmented-dispatch parity, the shard_map reference
# semantics, and the SearchService-level mesh smoke (parity, escape
# hatch, per-shard ladder isolation, drain re-routing).
# ---------------------------------------------------------------------------


def test_serving_devices_resolution_and_escape_hatch(monkeypatch):
    """serving_devices resolves None/"auto"/int requests and the
    FISHNET_NO_MESH=1 escape hatch clamps ANY request to one device."""
    import jax

    from fishnet_tpu.parallel.mesh import serving_devices

    monkeypatch.delenv("FISHNET_NO_MESH", raising=False)
    all_devs = list(jax.devices())
    assert serving_devices(None) == all_devs
    assert serving_devices("auto") == all_devs
    assert serving_devices(3) == all_devs[:3]
    assert serving_devices(all_devs[1:3]) == all_devs[1:3]
    monkeypatch.setenv("FISHNET_NO_MESH", "1")
    assert serving_devices("auto") == all_devs[:1]
    assert serving_devices(4) == all_devs[:1]


def test_shard_router_determinism_and_drain():
    """Group -> shard assignment is a pure function of (n_groups,
    n_shards); drain moves the dead shard's groups round-robin over the
    survivors, deterministically, and refuses to kill the last shard."""
    import pytest

    from fishnet_tpu.parallel.mesh import ShardRouter

    r1, r2 = ShardRouter(8, 4), ShardRouter(8, 4)
    assert [r1.shard_of(g) for g in range(8)] == [g % 4 for g in range(8)]
    assert [r1.shard_of(g) for g in range(8)] == [
        r2.shard_of(g) for g in range(8)
    ]
    assert r1.groups_of(1) == [1, 5]
    assert r1.group_count(2) == 2
    assert r1.alive_shards() == [0, 1, 2, 3]

    moved = r1.drain(1)
    assert moved == {1: 0, 5: 2}  # round-robin over survivors [0, 2, 3]
    assert r1.alive_shards() == [0, 2, 3]
    assert r1.shard_of(1) == 0 and r1.shard_of(5) == 2
    assert r1.groups_of(1) == []
    assert r2.drain(1) == moved  # same decision on an identical twin

    r1.drain(0)
    r1.drain(2)
    assert r1.alive_shards() == [3]
    assert all(r1.shard_of(g) == 3 for g in range(8))
    with pytest.raises(RuntimeError, match="no alive shard"):
        r1.drain(3)


def _shard_split_segments(rung, monkeypatch):
    """Fixture segments for the per-shard parity tests, reusing the
    coalescer suite's wire builders. The interpret rung shrinks the
    pallas chunk to 8 and uses plans whose deltas sit right after a
    chunk boundary with their anchor in the PREVIOUS chunk — and the
    4-segment arrangement puts a shard boundary (segment 2's start,
    global entry 12) in the middle of chunk [8, 16): the carry-in path
    is exercised across both chunk and shard boundaries."""
    from test_coalesce import _INTERPRET_PLANS, _PLANS, _make_segment

    rng = np.random.default_rng(53)
    size, tab_rows = 6, 4
    if rung == "fused-interpret":
        from fishnet_tpu.ops import ft_gather

        monkeypatch.setattr(ft_gather, "_CHUNK", 8)
        kw = {"interpret": True}
        plans = _INTERPRET_PLANS + _INTERPRET_PLANS
    else:
        kw = {"use_pallas": False}
        plans = _PLANS + _INTERPRET_PLANS[:1]
    segs = [_make_segment(p, size, tab_rows, rng) for p in plans]
    for s in segs:
        s["mat"] = (
            rng.integers(-400, 400, (size,)).astype(np.int32)
            if rung == "host-material" else None
        )
    return segs, size, kw


def _cat_segments(segs, size):
    """Concatenate a shard's segments into one segmented-dispatch wire
    (exactly SearchService._dispatch_segmented's stacking)."""
    tier = 4 * size + 4
    mats = None
    if segs[0]["mat"] is not None:
        mats = jnp.asarray(np.concatenate([s["mat"] for s in segs]))
    return (
        jnp.asarray(np.concatenate([s["packed"][:tier] for s in segs])),
        jnp.asarray(np.concatenate([s["buckets"] for s in segs])),
        jnp.asarray(np.concatenate([s["parent"] for s in segs])),
        mats,
        jnp.asarray(np.stack([s["tab"] for s in segs])),
        jnp.asarray(np.array([s["rows"] for s in segs], np.int32)),
        jnp.asarray(np.stack([s["ptab"] for s in segs])),
    )


@pytest.mark.parametrize("rung", ["xla", "fused-interpret", "host-material"])
def test_per_shard_dispatch_matches_fused_and_single(rung, monkeypatch):
    """The placement-aware serving invariant on every ladder rung: K
    segments dispatched as TWO per-shard segmented programs (the mesh
    coalescer's _flush-per-shard) return bit-for-bit the values and
    updated tables of the whole-mesh fused dispatch AND of K per-group
    single dispatches — sharding never changes a single bit."""
    from fishnet_tpu.nnue.jax_eval import (
        evaluate_packed_anchored,
        evaluate_packed_anchored_segmented,
    )

    params = params_from_weights(NnueWeights.random(seed=29))
    segs, size, kw = _shard_split_segments(rung, monkeypatch)
    tier = 4 * size + 4

    # Per-group references (XLA executor: every rung is bit-identical
    # per group, pinned at the op level by test_ops).
    refs = []
    for s in segs:
        v, nt, npt = evaluate_packed_anchored(
            params, jnp.asarray(s["packed"]), jnp.asarray(s["buckets"]),
            jnp.asarray(s["parent"]),
            None if s["mat"] is None else jnp.asarray(s["mat"]),
            jnp.asarray(s["tab"]),
            jnp.asarray(np.array([s["rows"]], np.int32)),
            jnp.asarray(s["ptab"]), use_pallas=False,
        )
        refs.append((np.asarray(v), np.asarray(nt), np.asarray(npt)))

    # One fused whole-mesh dispatch vs two per-shard dispatches.
    fused = evaluate_packed_anchored_segmented(
        params, *_cat_segments(segs, size), **kw
    )
    fused = tuple(map(np.asarray, fused))
    shard_out = []
    for shard_segs in (segs[:2], segs[2:]):
        v, nt, npt = evaluate_packed_anchored_segmented(
            params, *_cat_segments(shard_segs, size), **kw
        )
        shard_out.append((np.asarray(v), np.asarray(nt), np.asarray(npt)))

    for k, s in enumerate(segs):
        ref_v, ref_t, ref_pt = refs[k]
        sh, loc = divmod(k, 2)
        got_v, got_t, got_pt = shard_out[sh]
        assert np.array_equal(
            got_v[loc * size : loc * size + s["n"]], ref_v[: s["n"]]
        ), (rung, k, "per-shard values")
        assert np.array_equal(got_t[loc], ref_t), (rung, k, "anchor tab")
        assert np.array_equal(got_pt[loc], ref_pt), (rung, k, "psqt tab")
        assert np.array_equal(
            fused[0][k * size : k * size + s["n"]], ref_v[: s["n"]]
        ), (rung, k, "fused values")
        assert np.array_equal(fused[1][k], ref_t), (rung, k)
        assert np.array_equal(fused[2][k], ref_pt), (rung, k)


def test_sharded_segmented_evaluator_parity_and_no_collectives(monkeypatch):
    """The shard_map reference semantics for the serving topology:
    ShardedSegmentedEvaluator over 2 devices is bit-identical to the
    single-device segmented evaluator, its compiled HLO contains ZERO
    collectives (segment-locality makes every shard self-contained),
    and a segment count that does not divide over the mesh is rejected
    loudly."""
    import jax

    from fishnet_tpu.nnue.jax_eval import evaluate_packed_anchored_segmented
    from fishnet_tpu.parallel.mesh import ShardedSegmentedEvaluator

    params = params_from_weights(NnueWeights.random(seed=37))
    segs, size, _ = _shard_split_segments("host-material", monkeypatch)
    wire = _cat_segments(segs, size)

    evaluator = ShardedSegmentedEvaluator(devices=jax.devices()[:2])
    got = tuple(map(np.asarray, evaluator(params, *wire)))
    ref = tuple(map(np.asarray, evaluate_packed_anchored_segmented(
        params, *wire, use_pallas=False
    )))
    for g, r, what in zip(got, ref, ("values", "anchor tabs", "psqt tabs")):
        assert np.array_equal(g, r), f"sharded segmented diverged: {what}"

    hlo = (
        evaluator._fn_mat.lower(params, *wire).compile().as_text()
    )
    for collective in (
        "all-gather", "all-reduce", "all-to-all", "collective-permute",
        "ragged-all-to-all",
    ):
        assert collective not in hlo, f"sharded segmented emits {collective}"

    with pytest.raises(ValueError, match="does not divide"):
        bad = [segs[0], segs[1], segs[2]]
        evaluator(params, *_cat_segments(bad, size))


def _mesh_smoke(weights, mesh_devices):
    """One gated deterministic smoke run (the coalescer suite's
    discipline) on an optionally mesh-backed service, audited by the
    exactly-once ledger (every search acquired once, submitted once —
    clean even while shards degrade). Returns the analyses, the shard
    report, and whether the mesh path was active."""
    from test_coalesce import _SMOKE_FENS, _GatedService

    from fishnet_tpu.resilience import accounting
    from fishnet_tpu.search import eval_cache

    # Each smoke run cold-starts the process eval cache: consecutive
    # runs serve the SAME positions, and a warm cache would turn the
    # later services' dispatches into whole-batch skips — parity would
    # still hold (that's the cache's contract) but the traffic-spread
    # assertions below would see zero per-shard dispatches.
    eval_cache.reset_cache()
    svc = _GatedService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1, mesh_devices=mesh_devices,
    )
    ledger = accounting.install()
    try:
        svc.set_prefetch(0, adaptive=False)

        async def one(i, fen):
            ledger.record_acquired(f"mesh-{i}")
            r = await svc.search(fen, [], nodes=280)
            ledger.record_submitted(f"mesh-{i}")
            return r

        async def go():
            tasks = [
                asyncio.ensure_future(one(i, fen))
                for i, fen in enumerate(_SMOKE_FENS)
            ]
            await asyncio.sleep(0.3)
            svc.gate.set()
            return await asyncio.gather(*tasks)

        results = asyncio.run(go())
        ledger.assert_clean()
        analyses = [
            (
                r.best_move, r.depth, r.nodes,
                tuple(
                    (l.multipv, l.depth, l.is_mate, l.value, tuple(l.pv))
                    for l in r.lines
                ),
            )
            for r in results
        ]
        return analyses, svc.shard_report(), svc._router is not None
    finally:
        accounting.clear()
        svc.gate.set()
        svc.close()


def test_mesh_serving_parity_and_escape_hatch(monkeypatch):
    """Acceptance: the placement-aware mesh serves byte-identical
    analyses to the single-device path, spreads dispatches over more
    than one shard, and FISHNET_NO_MESH=1 restores the single-device
    service (router-less) byte-for-byte even when a mesh is
    requested."""
    monkeypatch.delenv("FISHNET_NO_MESH", raising=False)
    weights = NnueWeights.random(seed=7)

    single, rep1, meshed1 = _mesh_smoke(weights, None)
    assert not meshed1 and rep1["n_shards"] == 1

    sharded, rep2, meshed2 = _mesh_smoke(weights, "auto")
    assert meshed2 and rep2["n_shards"] > 1
    assert sum(1 for d in rep2["dispatches"] if d > 0) > 1, (
        f"traffic never spread over the mesh: {rep2['dispatches']}"
    )
    assert all(rep2["alive"]), rep2
    assert sharded == single, "mesh serving changed analysis output"

    monkeypatch.setenv("FISHNET_NO_MESH", "1")
    escaped, rep3, meshed3 = _mesh_smoke(weights, "auto")
    assert not meshed3 and rep3["n_shards"] == 1
    assert escaped == single, "FISHNET_NO_MESH=1 is not byte-for-byte"


def test_mesh_per_shard_ladder_isolation():
    """A device fault on ONE shard moves only that shard down its
    degradation ladder: siblings stay on the configured rung, every
    search completes, and the analyses match the un-faulted mesh run
    bit-for-bit (all rungs are bit-identical)."""
    from fishnet_tpu.resilience import faults

    weights = NnueWeights.random(seed=13)
    baseline, rep0, _ = _mesh_smoke(weights, "auto")
    rung0 = set(rep0["rungs"])
    assert len(rung0) == 1  # every shard starts on the configured rung

    faults.install("service.device_step:nth=1:error")
    try:
        faulted, rep1, _ = _mesh_smoke(weights, "auto")
    finally:
        faults.clear()

    degraded = [
        s for s in range(rep1["n_shards"])
        if rep1["rung_index"][s] != rep0["rung_index"][s]
    ]
    assert len(degraded) == 1, (
        f"ladder isolation broken: {rep0['rungs']} -> {rep1['rungs']}"
    )
    assert all(rep1["alive"]), "a single fault must degrade, not drain"
    assert rep1["rungs"][degraded[0]] != rep0["rungs"][degraded[0]]
    assert faulted == baseline, "per-shard degradation changed output"


def test_mesh_drain_reroutes_groups_to_siblings():
    """Walking one shard off the end of its ladder drains it: its
    groups re-route to surviving shards (tables migrate lazily at next
    dispatch), the report shows the shard dead, and the service keeps
    serving every search."""
    from test_coalesce import _SMOKE_FENS, _GatedService

    from fishnet_tpu.search.service import _MESH_RUNGS

    weights = NnueWeights.random(seed=17)
    svc = _GatedService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1, mesh_devices="auto",
    )
    try:
        svc.set_prefetch(0, adaptive=False)
        assert svc._router is not None and svc._n_shards > 1
        victim = 1
        victim_groups = svc._router.groups_of(victim)
        assert victim_groups
        err = RuntimeError("injected shard fault")
        # Ride the ladder to the bottom, then once more to drain.
        steps = len(_MESH_RUNGS) - svc._shard_rungs[victim]
        for _ in range(steps):
            svc._degrade_shard_for(victim_groups[0], err)
        rep = svc.shard_report()
        assert rep["alive"][victim] is False
        assert rep["rungs"][victim] == "drained"
        assert rep["groups"][victim] == []
        new_homes = {g: svc._router.shard_of(g) for g in victim_groups}
        assert all(s != victim for s in new_homes.values()), new_homes

        async def go():
            tasks = [
                asyncio.ensure_future(svc.search(fen, [], nodes=280))
                for fen in _SMOKE_FENS
            ]
            await asyncio.sleep(0.3)
            svc.gate.set()
            return await asyncio.gather(*tasks)

        results = asyncio.run(go())
        assert all(r.best_move and r.depth >= 1 for r in results)
        rep = svc.shard_report()
        # The pre-traffic drain means the dead shard never serves.
        assert rep["dispatches"][victim] == 0, rep["dispatches"]
    finally:
        svc.gate.set()
        svc.close()


async def test_sharded_packed_search_parity(anyio_backend):
    """The sharded PACKED wire (service-side per-shard repack +
    on-device expansion inside the shard_map) must reproduce the
    single-device backend's search results exactly — scores, mate
    flags, and best moves, position by position. Sequential submission
    + pinned prefetch, like every cross-backend parity suite (the TT
    evolution must be a deterministic function of the sequence)."""
    from fishnet_tpu.search.service import SearchService
    from tests.test_search import _parity_results, _random_fens

    weights = NnueWeights.random(seed=23)
    fens = _random_fens(10, seed=123)

    single = await _parity_results("jax", weights, fens, depth=3, prefetch=4)

    evaluator = ShardedEvaluator(
        params_from_weights(weights), mesh=make_mesh(), batch_capacity=64
    )
    svc = SearchService(
        weights=weights, pool_slots=16, batch_capacity=64,
        tt_bytes=64 << 20, evaluator=evaluator,
    )
    svc.set_prefetch(4, adaptive=False)
    try:
        assert svc._sharded_packed, "mesh path fell back to dense wire"
        sharded = []
        for fen in fens:
            r = await svc.search(fen, [], depth=3)
            line = [l for l in r.lines if l.multipv == 1][-1]
            sharded.append((line.value, line.is_mate, r.best_move))
    finally:
        svc.close()
    mismatches = [
        (fen, s, j) for fen, s, j in zip(fens, single, sharded) if s != j
    ]
    assert not mismatches, (
        f"{len(mismatches)} of {len(fens)} diverged; first: {mismatches[0]}"
    )
