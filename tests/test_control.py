"""Self-tuning control plane (doc/control-plane.md): signal folding
and hysteresis units, the bounded/revertible actuator registry, the
deterministic rule/probe policy (exact decision tables — the decision
path has no wall clock, so the same window sequence must replay the
same actions), degraded-shard skip, the ``FISHNET_NO_CONTROL``
byte-for-byte escape hatch, the ``burn_snapshot()`` SLO seam, the
subsystem actuation seams (service width/depth, shed watermarks, DRR
tenant weights), and the fleet console ``--control`` panel. The one
real-service test drives the controller end to end with injected
transport latency and checks the knob actually moved and reverted."""

import threading
from types import SimpleNamespace

import pytest

from fishnet_tpu.control import (
    Action,
    Actuator,
    ActuatorRegistry,
    Controller,
    ControlSignals,
    HysteresisSwitch,
    LadderProbe,
    NO_CONTROL_ENV,
    RuleProbePolicy,
    SignalCollector,
    control_enabled,
)
from fishnet_tpu.control.controller import WIDTH_LADDER, standard_actuators
from fishnet_tpu.control.signals import _StageAccum
from fishnet_tpu.telemetry.registry import MetricFamily, Sample


def _fam(name, rows, type="counter"):
    fam = MetricFamily(name=name, type=type, help="test fixture")
    for labels, value in rows:
        fam.samples.append(Sample(name=name, value=value, labels=labels))
    return fam


# ---------------------------------------------------------------------------
# Signal folding
# ---------------------------------------------------------------------------


def test_stage_accum_folds_across_threads():
    accum = _StageAccum()
    accum.observe("pack", 0.010)

    def worker():
        accum.observe("pack", 0.020)
        accum.observe("coalesce", 0.005)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    folded = accum.fold()
    assert folded["pack"][0] == pytest.approx(0.030)
    assert folded["pack"][1] == 2.0
    assert folded["coalesce"] == [pytest.approx(0.005), 1.0]


def test_hysteresis_switch_margin_and_hold():
    sw = HysteresisSwitch(margin=0.10, hold=2)
    # First observation seats the dominant immediately.
    assert sw.update({"pack": 0.6, "transport": 0.4}) == "pack"
    # A challenger inside the margin never starts a streak.
    assert sw.update({"pack": 0.46, "transport": 0.54}) == "pack"
    # Outside the margin it still needs `hold` consecutive windows.
    assert sw.update({"pack": 0.3, "transport": 0.7}) == "pack"
    assert sw.update({"pack": 0.3, "transport": 0.7}) == "transport"
    # One calm window resets the streak.
    assert sw.update({"pack": 0.3, "compute": 0.7}) == "transport"
    assert sw.update({"pack": 0.7, "compute": 0.3}) == "transport"
    assert sw.update({"pack": 0.3, "compute": 0.7}) == "transport"
    assert sw.update({"pack": 0.3, "compute": 0.7}) == "compute"


def test_collector_window_deltas_and_dominant():
    state = {"eval_steps": 0, "evals_shipped": 0, "cache_prewire_hits": 0}

    def counters():
        return dict(state)

    col = SignalCollector(counters_fn=counters)
    col.feed("dispatch_issue", 0.200)
    col.feed("coalesce", 0.100)
    col.feed("wire_decode", 0.050)
    state.update(eval_steps=40, evals_shipped=10, cache_prewire_hits=8)
    sig = col.sample()
    assert sig.window == 1
    assert sig.components["transport"] == pytest.approx(300.0)
    assert sig.components["decode_wait"] == pytest.approx(50.0)
    assert sig.dominant == "transport"
    assert sig.counters["eval_steps"] == 40.0
    assert sig.cache_hit_rate == pytest.approx(0.8)

    # The next window sees only the NEW durations and counter deltas.
    col.feed("dispatch_issue", 0.010)
    state.update(eval_steps=55)
    sig2 = col.sample()
    assert sig2.window == 2
    assert sig2.components["transport"] == pytest.approx(10.0)
    assert sig2.components["decode_wait"] == 0.0
    assert sig2.counters["eval_steps"] == 15.0

    # A silent window keeps the smoothed dominant, share 0.
    sig3 = col.sample()
    assert sig3.dominant == "transport"
    assert sig3.dominant_share == 0.0


def test_collector_baselines_shard_rungs():
    """A healthy service may idle mid-ladder (CPU serves from "xla"),
    so rung degradation is measured against the healthiest rung seen
    per shard, not against absolute rung 0."""
    report = {"rung_index": [1, 1], "occupancy": [0.5, 0.5]}
    svc = SimpleNamespace(
        shard_report=lambda: {k: list(v) for k, v in report.items()},
        counters=lambda: {},
    )
    col = SignalCollector(service=svc)
    assert col.sample().shard_rungs == [0, 0]
    report["rung_index"] = [1, 3]  # shard 1 degrades two rungs
    assert col.sample().shard_rungs == [0, 2]
    report["rung_index"] = [0, 1]  # shard 0 turns out to go lower
    assert col.sample().shard_rungs == [0, 0]
    report["rung_index"] = [1, 1]
    assert col.sample().shard_rungs == [1, 0]


# ---------------------------------------------------------------------------
# Actuator registry: bounds, revert, escape hatch
# ---------------------------------------------------------------------------


def test_registry_clamps_scalar_pair_and_map():
    calls = []
    reg = ActuatorRegistry()
    try:
        reg.register_all([
            Actuator("width", lambda v: calls.append(("width", v)),
                     lo=1, hi=8, default=2),
            Actuator("marks", lambda v: calls.append(("marks", v)),
                     lo=32, hi=4096, default=(256, 128)),
            Actuator("weights", lambda v: calls.append(("weights", v)),
                     lo=0.25, hi=4.0, default={}),
        ])
        assert reg.apply("width", 64).value == 8
        assert reg.apply("width", -3).value == 1
        assert reg.apply("marks", (8192, 8)).value == (4096, 32)
        assert reg.apply("weights", {"a": 9.0, "b": 0.01}).value == {
            "a": 4.0, "b": 0.25,
        }
        assert calls == [
            ("width", 8), ("width", 1),
            ("marks", (4096, 32)), ("weights", {"a": 4.0, "b": 0.25}),
        ]
        # Unknown knob and value-already-current are both no-ops.
        assert reg.apply("nope", 1) is None
        assert reg.apply("width", 1) is None
    finally:
        reg.close()


def test_registry_revert_restores_default():
    seen = []
    reg = ActuatorRegistry()
    try:
        reg.register(Actuator("depth", seen.append, lo=1, hi=4, default=2))
        assert reg.revert("depth") is None  # nothing applied yet
        entry = reg.apply("depth", 4, reason="test", window=7)
        assert (entry.direction, entry.window) == ("up", 7)
        back = reg.revert("depth")
        assert back.direction == "revert"
        assert seen == [4, 2]
        # Revert is one-shot until the knob moves again.
        assert reg.revert("depth") is None
        assert [e.knob for e in reg.recent()] == ["depth", "depth"]
    finally:
        reg.close()


def test_escape_hatch_refuses_apply_but_reverts(monkeypatch):
    seen = []
    reg = ActuatorRegistry()
    try:
        reg.register(Actuator("width", seen.append, lo=1, hi=8, default=2))
        monkeypatch.delenv(NO_CONTROL_ENV, raising=False)
        assert control_enabled()
        reg.apply("width", 8)
        monkeypatch.setenv(NO_CONTROL_ENV, "1")
        assert not control_enabled()
        assert reg.apply("width", 4) is None
        assert seen == [8]  # the refused apply never reached the setter
        # Restoring static defaults is exactly what the hatch promises.
        assert reg.revert_all()[0].value == 2
        assert seen == [8, 2]
    finally:
        reg.close()


def test_actuation_log_rides_the_metrics_registry():
    from fishnet_tpu.telemetry import REGISTRY

    reg = ActuatorRegistry()
    reg.register(Actuator(
        "t_log_knob", lambda v: None, lo=1, hi=8, default=1,
    ))
    reg.apply("t_log_knob", 4, window=3)

    def log_samples():
        out = []
        for fam in REGISTRY.collect():
            if fam.name == "fishnet_control_actuation_log":
                out.extend(
                    s for s in fam.samples
                    if s.labels.get("knob") == "t_log_knob"
                )
        return out

    rows = log_samples()
    assert len(rows) == 1
    assert rows[0].value == 3.0  # value carries the signal window
    assert rows[0].labels["direction"] == "up"
    assert rows[0].labels["to"] == "4"
    # Actuation counters ride the global registry alongside the log.
    fams = {f.name: f for f in REGISTRY.collect()}
    totals = fams["fishnet_control_actuations_total"]
    assert any(
        s.labels.get("knob") == "t_log_knob"
        and s.labels.get("direction") == "up" and s.value >= 1.0
        for s in totals.samples
    )
    reg.close()
    assert log_samples() == []  # close() unhooks the pull collector


def test_control_span_stage_registered():
    from fishnet_tpu.telemetry.spans import EVENT_STAGES

    assert "control" in EVENT_STAGES


# ---------------------------------------------------------------------------
# LadderProbe: deterministic hill-climb schedule
# ---------------------------------------------------------------------------


def test_ladder_probe_index_of():
    probe = LadderProbe()
    assert probe.ladder == WIDTH_LADDER
    assert probe.index_of(1) == 0
    assert probe.index_of(8) == 3
    assert probe.index_of(3) == 1  # off-ladder pins snap to nearest rung
    assert probe.index_of(100) == 3


def test_ladder_probe_keeps_improvements_and_narrows_first():
    probe = LadderProbe(settle=2, min_gain=0.05)
    idx = 2  # width 4
    # Two settle windows measure the reference, then a NARROWER trial.
    assert probe.update(idx, 10.0) is None
    assert probe.update(idx, 10.0) == (1, "trial")
    idx = 1
    # The trial improves ≥ min_gain: keep it, no move emitted.
    assert probe.update(idx, 12.0) is None
    assert probe.update(idx, 12.0) is None
    # Next measurement cycle continues downhill from the new rung.
    assert probe.update(idx, 12.0) is None
    assert probe.update(idx, 12.0) == (0, "trial")


def test_ladder_probe_reverts_and_backs_off_on_regression():
    probe = LadderProbe(settle=1, min_gain=0.05, max_hold=4)
    # Reference at rung 1, trial at rung 0 regresses -> step back.
    assert probe.update(1, 10.0) == (0, "trial")
    assert probe.update(0, 8.0) == (1, "revert")
    # Backoff: one hold window swallowed, then direction flips upward.
    assert probe.update(1, 10.0) is None
    assert probe.update(1, 10.0) == (2, "trial")
    # A second failure doubles the hold (capped at max_hold).
    assert probe.update(2, 5.0) == (1, "revert")
    assert probe.update(1, 10.0) is None
    assert probe.update(1, 10.0) is None
    assert probe.update(1, 10.0) == (0, "trial")


def test_ladder_probe_edge_rungs_flip_direction():
    probe = LadderProbe(settle=1)
    # At the bottom rung the narrower trial is impossible: flip up.
    assert probe.update(0, 10.0) == (1, "trial")


# ---------------------------------------------------------------------------
# RuleProbePolicy: exact decision tables
# ---------------------------------------------------------------------------


def _sig(window, dominant=None, share=0.0, counters=None, slo=None,
         cost=None, hit=0.0):
    sig = ControlSignals(window=window)
    sig.dominant = dominant
    sig.dominant_share = share
    sig.counters = dict(counters or {})
    sig.slo_status = dict(slo or {})
    sig.tenant_cost_share = dict(cost or {})
    sig.cache_hit_rate = hit
    return sig


def _run_width_schedule():
    """One fixed transport-dominant window sequence -> action list."""
    policy = RuleProbePolicy()
    policy.width_probe = LadderProbe(settle=2, min_gain=0.05)
    knobs = {"coalesce_width": 4, "pipeline_depth": None}
    scores = [10.0, 10.0, 8.0, 8.0, 10.0, 10.0]
    out = []
    for w, score in enumerate(scores, start=1):
        sig = _sig(w, dominant="transport", share=0.9,
                   counters={"eval_steps": score})
        actions = policy.decide(sig, dict(knobs))
        for a in actions:
            knobs[a.knob] = a.value  # pretend the registry applied it
        out.append(tuple((a.knob, a.value, a.reason) for a in actions))
    return out


def test_policy_width_probe_decision_table():
    table = _run_width_schedule()
    # Windows 1-2 measure; window 2 emits the narrower trial; the
    # regressed trial steps back at window 4; backoff swallows 5-6.
    assert table[0] == ()
    assert table[1] == ((
        "coalesce_width", 2,
        "transport-dominated (90%): probe trial",
    ),)
    assert table[3] == ((
        "coalesce_width", 4,
        "transport-dominated (90%): trial regressed, step back",
    ),)
    assert table[2] == table[4] == table[5] == ()
    # Determinism: the same window sequence replays the same actions.
    assert table == _run_width_schedule()


def test_policy_decode_queue_deepens_pipeline():
    policy = RuleProbePolicy()
    sig = _sig(1, counters={"decode_queue": 3.0, "eval_steps": 5.0})
    actions = policy.decide(sig, {"pipeline_depth": 2})
    assert actions == [Action(
        "pipeline_depth", 3, "standing decode queue: deepen the async "
        "pipeline",
    )]
    # The rule respects the depth cap.
    assert policy.decide(sig, {"pipeline_depth": 4}) == []


def test_policy_slo_burn_tightens_and_downweights():
    policy = RuleProbePolicy()
    sig = _sig(1, slo={"move_latency": "burning"},
               cost={"hog": 0.8, "meek": 0.2})
    actions = policy.decide(sig, {
        "shed_watermark": (256, 128), "tenant_weights": {},
    })
    assert ("shed_watermark", (128, 64)) in [
        (a.knob, a.value) for a in actions
    ]
    assert ("tenant_weights", {"hog": 0.5}) in [
        (a.knob, a.value) for a in actions
    ]
    # At the floor the watermark stops tightening; a balanced cost
    # book never downweights anybody.
    calm = policy.decide(
        _sig(2, slo={"x": "breach"}, cost={"a": 0.5, "b": 0.5}),
        {"shed_watermark": (64, 32), "tenant_weights": {}},
    )
    assert calm == []


def test_policy_prefetch_pin_unpin():
    policy = RuleProbePolicy()
    live = {"eval_steps": 10.0}
    pin = policy.decide(
        _sig(1, counters=live, hit=0.7), {"prefetch_budget": None}
    )
    assert pin == [Action(
        "prefetch_budget", 0, "cache hot (70%): pin prefetch off",
    )]
    unpin = policy.decide(
        _sig(2, counters=live, hit=0.1), {"prefetch_budget": 0}
    )
    assert unpin == [Action(
        "prefetch_budget", None, "cache cold (10%): restore adaptive "
        "prefetch",
    )]
    # Inside the hysteresis band nothing moves either way.
    assert policy.decide(
        _sig(3, counters=live, hit=0.5), {"prefetch_budget": 0}
    ) == []


def test_policy_calm_stepback_waits_for_quiescence():
    policy = RuleProbePolicy(calm_hold=3)
    knobs = {"coalesce_width": 2, "pipeline_depth": None,
             "prefetch_budget": 0}
    # hit=0.5 sits inside the pin/unpin hysteresis band, so the
    # prefetch rule stays silent while the pin is in place.
    quiet = lambda w: _sig(w, hit=0.5)  # noqa: E731 - no traffic
    assert policy.decide(quiet(1), knobs) == []
    assert policy.decide(quiet(2), knobs) == []
    # A live window resets the calm streak.
    assert policy.decide(
        _sig(3, counters={"eval_steps": 4.0}, hit=0.5), knobs
    ) == []
    assert policy.decide(quiet(4), knobs) == []
    assert policy.decide(quiet(5), knobs) == []
    # Third consecutive quiet window: step ONE knob back — and never
    # the prefetch pin, which the hit-rate rule owns.
    assert policy.decide(quiet(6), knobs) == [Action(
        "coalesce_width", None, "calm for 3 windows: step back",
    )]


# ---------------------------------------------------------------------------
# Controller: degraded-shard skip
# ---------------------------------------------------------------------------


class _Feed:
    """Stub collector replaying crafted ControlSignals windows."""

    def __init__(self, sigs):
        self._sigs = list(sigs)

    def sample(self):
        return self._sigs.pop(0)


class _Fixed:
    def __init__(self, actions):
        self._actions = list(actions)

    def decide(self, sig, knobs):
        return list(self._actions)


def test_controller_skips_degraded_shards():
    calls = []

    def setter(value, shards=None):
        calls.append((value, shards))

    sigs = []
    for rungs in ([0, 0], [0, 1], [2, 1]):
        sig = ControlSignals(window=len(sigs) + 1)
        sig.shard_rungs = list(rungs)
        sigs.append(sig)
    reg = ActuatorRegistry()
    try:
        reg.register(Actuator(
            "coalesce_width", setter, lo=1, hi=8, default=None,
            shard_scoped=True,
        ))
        ctrl = Controller(
            _Feed(sigs), reg,
            policy=_Fixed([Action("coalesce_width", 8, "test")]),
        )
        # All healthy: actuate every shard (shards=None).
        assert len(ctrl.step()) == 1
        # One shard mid-degradation: actuate only the healthy one —
        # the degradation ladder already owns the sick shard's knob.
        assert len(ctrl.step()) == 1
        # Every shard degraded: the action is skipped outright.
        assert ctrl.step() == []
        assert calls == [(8, None), (8, [0])]
        assert ctrl.last_signals.shard_rungs == [2, 1]
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# SLO burn_snapshot seam
# ---------------------------------------------------------------------------


def test_burn_snapshot_statuses_from_synthetic_families():
    from fishnet_tpu.telemetry.slo import SLO, Selector, SLOEngine

    slo = SLO(
        name="t_success", description="test", objective=0.99,
        total=Selector("t_requests_total"),
        bad=Selector("t_requests_total", {"outcome": "error"}),
    )
    eng = SLOEngine(slos=[slo], windows=(60.0, 300.0))
    fams = {"t_requests_total": _fam("t_requests_total", [
        ({"outcome": "ok"}, 100.0),
    ])}
    first = eng.burn_snapshot(families=fams, now=0.0)
    assert set(first) == {"t_success"}
    assert first["t_success"]["status"] == "ok"

    fams = {"t_requests_total": _fam("t_requests_total", [
        ({"outcome": "ok"}, 150.0), ({"outcome": "error"}, 50.0),
    ])}
    hot = eng.burn_snapshot(families=fams, now=30.0)["t_success"]
    # Half the window's traffic errored against a 1% budget: every
    # window burns, so the status escalates straight to breach.
    assert hot["status"] == "breach"
    assert all(burn > 1.0 for burn in hot["windows"].values())


def test_burn_snapshot_defaults_to_local_registry():
    from fishnet_tpu.telemetry.slo import SLOEngine

    snap = SLOEngine().burn_snapshot()
    assert "move_latency" in snap and "api_success" in snap
    assert all(
        entry["status"] in ("ok", "burning", "breach")
        for entry in snap.values()
    )


# ---------------------------------------------------------------------------
# Subsystem actuation seams
# ---------------------------------------------------------------------------


def test_shed_policy_set_watermarks():
    from fishnet_tpu.resilience.shedding import ShedPolicy

    shed = ShedPolicy(high_watermark=256)
    assert (shed.high_watermark, shed.low_watermark) == (256, 128)
    shed.set_watermarks((128, 64))  # the registry's pair-knob shape
    assert (shed.high_watermark, shed.low_watermark) == (128, 64)
    shed.set_watermarks(512)  # scalar: low re-derives as high // 2
    assert (shed.high_watermark, shed.low_watermark) == (512, 256)
    shed.set_watermarks((100, 400))  # low clamps to at most high
    assert (shed.high_watermark, shed.low_watermark) == (100, 100)


def test_lane_scheduler_tenant_weights_reshape_refill():
    from fishnet_tpu.sched.queue import LaneScheduler
    from fishnet_tpu.resilience.shedding import LANE_THROUGHPUT

    def pos(tenant, i):
        return SimpleNamespace(
            work=SimpleNamespace(id=tenant), position_id=i
        )

    def drain_order(weights):
        sched = LaneScheduler(quantum=2)
        for i in range(4):
            sched.push(pos("a", i), "a", LANE_THROUGHPUT)
            sched.push(pos("b", i), "b", LANE_THROUGHPUT)
        sched.set_tenant_weights(weights)
        assert sched.tenant_weights() == (weights or {})
        return [sched.pop().work.id for _ in range(8)]

    # Unweighted DRR: alternating turns of `quantum` positions.
    assert drain_order(None) == ["a", "a", "b", "b"] * 2
    # Weight 2.0 doubles a's refill; 0.5 would halve it (min 1).
    assert drain_order({"a": 2.0}) == [
        "a", "a", "a", "a", "b", "b", "b", "b",
    ]
    assert drain_order({"a": 0.5}) == [
        "a", "b", "b", "a", "b", "b", "a", "a",
    ]


def test_standard_actuators_bind_fake_subsystems():
    svc = SimpleNamespace(
        set_coalesce_width=lambda v, shards=None: None,
        coalesce_width=lambda: 4,
        set_async_depth=lambda v: None,
        async_depth=lambda: 2,
        set_prefetch=lambda v, adaptive=True: None,
    )
    shed = SimpleNamespace(
        high_watermark=256, low_watermark=128,
        set_watermarks=lambda pair: None,
    )
    pool = SimpleNamespace(
        leaf_width_max=lambda: 16, set_leaf_width_max=lambda v: None,
    )
    sched = SimpleNamespace(
        set_tenant_weights=lambda w: None, tenant_weights=lambda: {},
    )
    acts = {a.name: a for a in standard_actuators(
        service=svc, shed_policy=shed, mcts_pool=pool, scheduler=sched,
    )}
    assert set(acts) == {
        "coalesce_width", "pipeline_depth", "prefetch_budget",
        "shed_watermark", "mcts_leaf_max", "tenant_weights",
    }
    assert acts["coalesce_width"].shard_scoped
    # Defaults are captured at BIND time — that is what revert and the
    # escape hatch restore.
    assert acts["pipeline_depth"].default == 2
    assert acts["shed_watermark"].default == (256, 128)
    assert acts["mcts_leaf_max"].default == 16
    reg = ActuatorRegistry()
    try:
        reg.register_all(acts.values())
        snap = reg.snapshot()
        assert snap["coalesce_width"] == 4  # live getter, not default
        assert snap["tenant_weights"] == {}
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# Fleet console --control panel
# ---------------------------------------------------------------------------


def test_fleet_control_panel_renders_log():
    from fishnet_tpu.telemetry.fleet import _control_panel

    st = SimpleNamespace(profile=None, families={
        "fishnet_control_actuations_total": _fam(
            "fishnet_control_actuations_total",
            [({"knob": "coalesce_width", "direction": "down"}, 3.0),
             ({"knob": "pipeline_depth", "direction": "up"}, 2.0)],
        ),
        "fishnet_control_actuation_log": _fam(
            "fishnet_control_actuation_log",
            [({"seq": "2", "knob": "pipeline_depth", "direction": "up",
               "to": "3"}, 12.0),
             ({"seq": "1", "knob": "coalesce_width",
               "direction": "down", "to": "2"}, 9.0)],
            type="gauge",
        ),
    })
    bare = SimpleNamespace(profile=None, families={})
    lines = _control_panel([("w0", st), ("w1", bare)])
    text = "\n".join(lines)
    assert "w0" in text and "5 actuations" in text
    # Log rows render oldest-first by per-proc actuation seq.
    assert text.index("coalesce_width") < text.index("pipeline_depth")
    assert "w9" in text and "-> 2" in text
    assert "w1" in text and "control plane off" in text


# ---------------------------------------------------------------------------
# End to end against a real service
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live_service():
    import time

    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.search.service import SearchService

    svc = SearchService(
        weights=NnueWeights.random(seed=7), pool_slots=8,
        batch_capacity=256, tt_bytes=8 << 20, backend="jax",
        pipeline_depth=4, driver_threads=1,
    )
    try:
        # Wait for the warmup dispatch probe to land: until it does
        # the coalescer cannot recompute a width after an override
        # clears, so the revert assertions below would be meaningless.
        co = svc._coalescer
        if co is not None:
            deadline = time.monotonic() + 60.0
            while co._probe is None and time.monotonic() < deadline:
                time.sleep(0.05)
        yield svc
    finally:
        svc.close()


def test_service_knob_seams(live_service):
    svc = live_service
    d0 = svc.async_depth()
    if d0 is None:
        pytest.skip("synchronous dispatch mode: no async depth knob")
    svc.set_async_depth(4)
    assert svc.async_depth() == 4
    svc.set_async_depth(1)
    assert svc.async_depth() == 1
    svc.set_async_depth(None)  # None restores the static default
    assert svc.async_depth() == d0

    w0 = svc.coalesce_width()
    if w0 is None:
        pytest.skip("coalescing disabled: no width knob")
    svc.set_coalesce_width(2)
    assert svc.coalesce_width() == 2
    svc.set_coalesce_width(None)
    assert svc.coalesce_width() == w0


def test_controller_end_to_end_on_real_service(live_service, monkeypatch):
    """Injected transport latency shifts the critical path; the
    controller probes the REAL service's coalesce width, and the
    escape hatch + revert restore the pre-controller state exactly."""
    svc = live_service
    monkeypatch.delenv(NO_CONTROL_ENV, raising=False)
    w0 = svc.coalesce_width()
    d0 = svc.async_depth()
    if w0 is None or d0 is None:
        pytest.skip("coalescer or async pipeline disabled")

    state = {"eval_steps": 0}

    def fake_counters():
        state["eval_steps"] += 40
        return dict(state)

    collector = SignalCollector(service=svc, counters_fn=fake_counters)
    registry = ActuatorRegistry()
    try:
        registry.register_all([
            a for a in standard_actuators(service=svc)
            if a.name in ("coalesce_width", "pipeline_depth")
        ])
        policy = RuleProbePolicy()
        policy.width_probe = LadderProbe(settle=1)
        ctrl = Controller(collector, registry, policy=policy)

        collector.feed("dispatch_issue", 0.050)
        collector.feed("coalesce", 0.020)
        applied = ctrl.step()
        assert [a.knob for a in applied] == ["coalesce_width"]
        # The probe's first move from w0 is deterministic: narrower
        # when possible, else the bottom rung flips upward.
        ref = LadderProbe(settle=1)
        nxt, kind = ref.update(ref.index_of(w0), 40.0)
        assert kind == "trial"
        assert svc.coalesce_width() == WIDTH_LADDER[nxt]

        # Escape hatch: decisions stop, revert restores w0 exactly.
        monkeypatch.setenv(NO_CONTROL_ENV, "1")
        collector.feed("dispatch_issue", 0.050)
        assert ctrl.step() == []
        registry.revert_all()
        assert svc.coalesce_width() == w0
        assert svc.async_depth() == d0
    finally:
        registry.close()
        collector.detach()
